"""In-process protocol driver: a leader and two colocated server states.

The correctness backbone of the framework — both servers' state machines run
in one process (the integration-test shape the reference intended with its
commented-out ``collect_test.rs``, SURVEY.md §4), with the trusted-exchange
data plane: the per-(node,client) packed share bits are compared directly
instead of passing through the GC+OT 2PC (functionally identical counts —
exactly what the leader reconstructs anyway via ``keep_values``,
ref: collect.rs:945-964 — with semi-honest security dropped).  The secure
data plane drops in behind the same ``counts_by_pattern`` seam.

Level-loop semantics mirror the reference leader (ref: leader.rs:185-297):

- threshold = ``max(1, threshold · nreqs)`` per level (leader.rs:193-194);
- ``data_len - 1`` inner levels then one last level (leader.rs:417-438);
- prune keeps only above-threshold children (leader.rs:229-234);
- paths decode MSB-first per dim; heavy hitters are the surviving leaves.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field

import jax
import numpy as np
from jax import lax

from .. import obs as obsmod
from ..obs import metrics as obsmetrics
from ..ops.ibdcf import EvalState, IbDcfKeyBatch
from . import collect


def cw_window(keys: IbDcfKeyBatch, lo: int, hi: int):
    """Host-side correction-word WINDOW [lo, hi) -> device upload,
    LEVEL-MAJOR (``[W, N, d, 2, words]``).

    For the STREAMING crawl mode: ``keys`` leaves are host numpy arrays
    (the full ``cw_seed [N, d, 2, L, 4]`` never touches the device); the
    crawl uploads ~20 B per (client, dim, side, level) in windows of
    ``Leader.stream_window`` levels and slices each level ON DEVICE
    (:func:`cw_at`).  Windowing matters twice over a remote-chip tunnel:
    eight big transfers beat 512 small ones, and per-``device_put``
    buffer churn in the remote runtime was measured to creep ~20 MB per
    level until a 450-level crawl died of ResourceExhausted.  The
    level-major transpose happens on the HOST so the per-level device
    slice is one contiguous 13 MB view — slicing the natural
    ``[..., W, words]`` layout instead was a strided gather over the
    whole window and cost ~2 s/level on chip."""
    def take(a):
        # fhh-lint: disable=host-sync-in-hot-loop (keys are host-resident
        # by design in streaming mode; this IS the windowed upload)
        win = np.asarray(a)[..., lo:hi, :]
        return jax.device_put(np.ascontiguousarray(np.moveaxis(win, -2, 0)))
    return take(keys.cw_seed), take(keys.cw_bits), take(keys.cw_y_bits)


@jax.jit
def _cw_at(window, i):
    return tuple(
        lax.dynamic_index_in_dim(a, i, axis=0, keepdims=False) for a in window
    )


def cw_at(window, idx: int):
    """One level's cw triple out of a level-major device window (one
    contiguous device slice — no host transfer)."""
    return _cw_at(window, np.int32(idx))


def slim_root_batch(keys: IbDcfKeyBatch) -> IbDcfKeyBatch:
    """Root-only key batch for ``tree_init`` in streaming mode: real
    root seeds + key_idx, zero-length correction-word axes (eval_init
    touches only the roots; uploading the full cw tensors is exactly what
    streaming exists to avoid)."""
    root = np.asarray(keys.root_seed)
    batch = root.shape[:-1]
    return IbDcfKeyBatch(
        key_idx=np.asarray(keys.key_idx),
        root_seed=root,
        cw_seed=np.zeros(batch + (0, 4), np.uint32),
        cw_bits=np.zeros(batch + (0, 2), bool),
        cw_y_bits=np.zeros(batch + (0, 2), bool),
    )


@dataclass
class ServerState:
    """One collector server's state (ref: server.rs:44-52 wraps the same)."""

    keys: IbDcfKeyBatch  # [N, d, 2]
    alive_keys: np.ndarray  # bool[N] liveness flags (ref: collect.rs:32)
    frontier: collect.Frontier | None = None
    children: object | None = None  # expand-time child-state cache


@dataclass
class CrawlResult:
    paths: np.ndarray  # bool[H, d, L] per-dim MSB-first paths
    counts: np.ndarray  # uint32[H]

    def decode_ints(self) -> np.ndarray:
        """paths -> int[H, d] leaf values (MSB-first per dim).

        Domains of 63+ bits (the COVID f64-bit encoding is 64) overflow
        an int64 weight vector, so wide paths decode through Python ints
        (object dtype) — this is leader-side decoration, not a hot path."""
        L = self.paths.shape[-1]
        if L < 63:
            weights = 1 << np.arange(L - 1, -1, -1)
            return (self.paths.astype(np.int64) * weights).sum(-1)
        vals = np.zeros(self.paths.shape[:-1], dtype=object)
        for i in range(L):
            vals = (vals << 1) | self.paths[..., i].astype(object)
        return vals


@dataclass
class Leader:
    """Drives two ServerStates level by level (ref: leader.rs:185-297)."""

    server0: ServerState
    server1: ServerState
    n_dims: int
    data_len: int
    f_max: int = 256
    min_bucket: int = 1  # pin >1 only on compile-bound test hosts
    # STREAMING mode: keys stay in host RAM; each level uploads only its
    # cw slice (double-buffered) and the crawl re-expands survivors
    # instead of caching children — the regime for key batches / wide
    # frontiers that exceed one chip's HBM (data_len=512 at >200k
    # clients with both servers colocated).
    stream: bool = False
    # streaming-advance transient bound: parent slots expanded per chunk
    # (None = whole bucket at once; set on HBM-bound runs, see
    # collect.advance_from_cw)
    stream_chunk: int | None = None
    # cw upload window in levels (see cw_window); the next window is
    # prefetched at the current window's entry so the transfer rides
    # behind ~stream_window levels of compute
    stream_window: int = 64
    # radix-2^k level fusion (Config.crawl_radix_bits): bits crawled per
    # round; each run_level call covers bit levels [level, level+r) with
    # r = min(radix, data_len - level).  Pruning is on the depth-(base+r)
    # counts, bit-identical to r sequential levels (monotone counts make
    # the intermediate prunes subsumed — collect.py radix section).
    # Streaming mode pins radix=1 (advance_from_cw re-expands one bit).
    radix: int = 1
    # leader-side bookkeeping
    paths: np.ndarray = field(default=None)  # bool[F, d, level]
    n_nodes: int = 0
    # telemetry: per-level phase timers + survivor gauges + checkpoint
    # events; the heartbeat thread names the level a wedged crawl died in
    obs: obsmetrics.Registry = None

    def __post_init__(self):
        if self.obs is None:
            self.obs = obsmetrics.Registry("driver")
        collect.check_radix(self.n_dims, self.radix)
        if self.stream and self.radix > 1:
            raise ValueError(
                "streaming crawl mode pins crawl_radix_bits=1 "
                "(advance_from_cw re-expands one bit per level)"
            )

    def tree_init(self):
        for s in (self.server0, self.server1):
            keys = slim_root_batch(s.keys) if self.stream else s.keys
            s.frontier = collect.tree_init(keys, self.min_bucket)
            s.children = None
        self.paths = np.zeros((1, self.n_dims, 0), bool)
        self.n_nodes = 1
        self._win = {}  # which -> (lo, window triple)
        self._win_next = {}  # (which, lo) -> prefetched window triple

    def _take_cw(self, which: int, level: int):
        W = self.stream_window
        lo = (level // W) * W
        ent = self._win.get(which)
        if ent is None or ent[0] != lo:
            tri = self._win_next.pop((which, lo), None)
            if tri is None:
                keys = (self.server0, self.server1)[which].keys
                tri = cw_window(keys, lo, min(lo + W, self.data_len))
            self._win[which] = ent = (lo, tri)
            # start the NEXT window's upload now — it arrives behind ~W
            # levels of compute
            nlo = lo + W
            if nlo < self.data_len and (which, nlo) not in self._win_next:
                keys = (self.server0, self.server1)[which].keys
                self._win_next[(which, nlo)] = cw_window(
                    keys, nlo, min(nlo + W, self.data_len)
                )
        return cw_at(ent[1], level - ent[0])

    def run_level(self, level: int, nreqs: int, threshold: float) -> int:
        """One crawl->threshold->prune round; returns surviving node count.

        Trusted-exchange mode: counts are exact (the reconstruction
        ``v0 - v1`` of ref collect.rs:945-964, computed directly).

        ``level`` is the BASE bit level of the round; with ``radix`` > 1
        the round fuses bit levels [level, level + r) for
        r = min(radix, data_len - level) — one expand, one count, one
        prune over the 2^(r·d) fused children.
        """
        d = self.n_dims
        r = min(self.radix, self.data_len - level)
        masks = collect.pattern_masks_radix(d, r)
        with self.obs.span("level", level=level):
            with self.obs.span("fss", level=level):
                if self.stream:
                    cw0 = self._take_cw(0, level)
                    cw1 = self._take_cw(1, level)
                    p0, _ = collect.expand_share_bits_from_cw(
                        cw0, self.server0.frontier, want_children=False
                    )
                    p1, _ = collect.expand_share_bits_from_cw(
                        cw1, self.server1.frontier, want_children=False
                    )
                else:
                    p0, ch0 = collect.expand_share_bits_radix(
                        self.server0.keys, self.server0.frontier, level, r
                    )
                    p1, ch1 = collect.expand_share_bits_radix(
                        self.server1.keys, self.server1.frontier, level, r
                    )
                    self.server0.children, self.server1.children = ch0, ch1
            with self.obs.span("field", level=level):
                counts = collect.counts_by_pattern(
                    p0,
                    p1,
                    masks,
                    self.server0.alive_keys,  # host bool[N] as-is
                    self.server0.frontier.alive,
                )
                self.obs.count("device_fetches")
                # the ONE deliberate per-level readback: the threshold
                # decision and prune bookkeeping are leader/host logic
                # fhh-lint: disable=host-sync-in-hot-loop (counted above)
                counts = np.asarray(counts)  # [F, 2^d]

                thresh = max(1, int(threshold * nreqs))  # ref: leader.rs:193-194
                # walk fused children in the k=1 visit order (earlier
                # steps most significant) so survivor order — and the
                # f_max truncation set — is bit-identical to r sequential
                # levels (collect.radix_pattern_order; identity at r=1)
                order = collect.radix_pattern_order(d, r)
                keep = counts[:, order] >= thresh  # [F, 2^(r·d)]
                keep[self.n_nodes :, :] = False
                parent, rank, n_alive = collect.compact_survivors(
                    keep, self.f_max, self.min_bucket
                )
                pattern = order[rank]
                pat_bits = collect.pattern_to_bits_radix(pattern, d, r)

            with self.obs.span("advance", level=level):
                if self.stream:
                    del p0, p1  # frontier buffers are donated by advance_from_cw
                    if level < self.data_len - 1 and n_alive:
                        f0, f1 = self.server0.frontier, self.server1.frontier
                        self.server0.frontier = None  # drop refs before donation
                        self.server1.frontier = None
                        self.server0.frontier = collect.advance_from_cw(
                            cw0, f0, parent, pat_bits[:, 0, :], n_alive,
                            self.stream_chunk
                        )
                        # free server 0's old frontier BEFORE server 1 advances:
                        # keeping both olds + both news alive is what overflows
                        # HBM at wide-frontier levels (four full frontiers)
                        del f0
                        self.server1.frontier = collect.advance_from_cw(
                            cw1, f1, parent, pat_bits[:, 0, :], n_alive,
                            self.stream_chunk
                        )
                        del f1
                else:
                    for s in (self.server0, self.server1):
                        s.frontier = collect.advance_from_children_radix(
                            s.children, parent, pat_bits, n_alive, r
                        )
                        s.children = None

            # leader-side path bookkeeping (step t's bit for dim j =
            # (pattern >> (t·d + j)) & 1 — the fused path appends r bits
            # per dim, step-major)
            new_paths = np.zeros((n_alive, d, self.paths.shape[-1] + r), bool)
            for i in range(n_alive):
                new_paths[i, :, : -r] = self.paths[parent[i]]
                for t in range(r):
                    new_paths[i, :, -r + t] = pat_bits[i, t]
            self.paths = new_paths
            self.n_nodes = n_alive
            self.obs.gauge("survivors", n_alive, level=level)
            self._last_counts = counts[parent[:n_alive], pattern[:n_alive]]
        return n_alive

    def run(
        self,
        nreqs: int,
        threshold: float,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 64,
        resume: bool = False,
    ) -> CrawlResult:
        """Full crawl: init + data_len levels + final reconstruction
        (ref: leader.rs:417-438 then final_shares at :282-297).

        ``checkpoint_path`` + ``checkpoint_every`` persist the crawl state
        every N completed levels (see :meth:`checkpoint`); ``resume=True``
        restores from that file (if present) and continues from the next
        level instead of starting over — a 512-level flagship crawl is
        minutes of wall-clock, and the reference offers nothing but a full
        restart on interruption (its only recovery verb is ``reset``,
        server.rs:64-69).  Keys are NOT in the checkpoint (they are the
        bulk of the bytes and the caller already holds them); construct
        the Leader with the same key batches before resuming.  A completed
        crawl REMOVES its checkpoint file, so the natural crash-safe
        invocation (always pass the same path with ``resume=True``) starts
        the next crawl fresh instead of silently resuming a finished one."""
        if (resume and checkpoint_path is not None
                and os.path.exists(checkpoint_path)):
            start = self.restore(checkpoint_path, nreqs, threshold)
        else:
            start = 0
            self.tree_init()

        def done(result):
            if checkpoint_path is not None and os.path.exists(checkpoint_path):
                os.remove(checkpoint_path)
            return result

        # cadence clamped so SHORT crawls still checkpoint mid-crawl: with
        # the raw default (64) a data_len <= 64 run would only ever hit
        # the final level — which the guard below rightly skips (a
        # finished crawl has nothing to resume) — and silently write
        # nothing at all
        every = min(checkpoint_every, max(1, self.data_len // 2))
        for level in range(start, self.data_len, self.radix):
            r = min(self.radix, self.data_len - level)
            n = self.run_level(level, nreqs, threshold)
            if n == 0:
                return done(CrawlResult(
                    paths=np.zeros((0, self.n_dims, level + r), bool),
                    counts=np.zeros(0, np.uint32),
                ))
            if (
                checkpoint_path is not None
                and level + r < self.data_len
                and (level + r) % every == 0
            ):
                self.checkpoint(checkpoint_path, level, nreqs, threshold)
        return done(CrawlResult(paths=self.paths, counts=self._last_counts))

    # -- checkpoint / resume -------------------------------------------------

    def _key_fingerprint(self) -> np.ndarray:
        """SHA-256 over both servers' key identities: key_idx + root seeds
        PLUS an every-client checksum of the correction-word planes across
        ALL levels.  Root seeds alone are not an identity — two keygen runs
        sharing an RNG seed but differing in ball radius (or any other
        keygen parameter) produce identical roots with different
        correction words — and the level axis must be complete: the
        radius perturbs the LOW bits of the interval endpoints, so the
        first differing cw sits at the deepest levels, not level 0
        (measured: ball 1 vs 2 at L=5 diverges only from level 3 down).
        The client axis must be complete too — ANY client sample (prefix
        or spread) admits two batches that diverge only at unsampled
        clients — so each cw plane is collapsed by a position-weighted
        mod-2^32 checksum over the client axis BEFORE the fetch: every
        client contributes (odd weights are invertible mod 2^32, so a
        change in any single client's plane always moves the sum), while
        the device->host transfer stays the reduced plane (~16 KB at
        L=512 vs ~2 MB per-client — tunnel-priced either way).  Cached:
        keys are immutable for the crawl's lifetime."""
        fp = getattr(self, "_key_fp", None)
        if fp is None:
            import jax.numpy as jnp

            # Phase 1 — compute every piece WITHOUT fetching: per server,
            # the identity arrays (key_idx, root_seed) plus one reduced
            # plane per cw tensor.  All-level cw planes: seeds
            # [N, d, 2, L, 4] plus the t/y bit planes [N, d, 2, L, 2] (a
            # divergence at any level lands in at least one); reduce with
            # the array's own backend — streaming mode holds host keys,
            # uploading them just to reduce would defeat the point — and
            # in client CHUNKS: at the flagship 196k x L=512 shape a
            # full-batch weighted product would transiently double the
            # ~3 GB plane in host RAM (or HBM, which the crawl already
            # runs near the limit of) at checkpoint time.
            fetch: list = []  # device/host arrays, ONE stacked device_get
            layout: list = []  # hash order: ("arr", fetch_i) | ("red", red_i)
            device_reds: list = []  # raveled on-device reductions
            for s in (self.server0, self.server1):
                for ident in (s.keys.key_idx, s.keys.root_seed):
                    layout.append(("arr", len(fetch)))
                    fetch.append(ident)
                n = s.keys.key_idx.shape[0]
                for plane in (s.keys.cw_seed, s.keys.cw_bits, s.keys.cw_y_bits):
                    on_device = isinstance(plane, jax.Array)
                    xp = jnp if on_device else np
                    red = None
                    for i in range(0, n, 4096):
                        p = xp.asarray(plane[i : i + 4096], dtype=xp.uint32)
                        w = (
                            xp.arange(i, i + p.shape[0], dtype=xp.uint32) * 2
                            + 1
                        ).reshape((p.shape[0],) + (1,) * (p.ndim - 1))
                        part = (p * w).sum(axis=0, dtype=xp.uint32)
                        red = part if red is None else red + part
                    if on_device:
                        layout.append(("red", len(device_reds)))
                        device_reds.append(red.ravel())
                    else:
                        layout.append(("arr", len(fetch)))
                        fetch.append(red)
            # Phase 2 — ONE stacked transfer (was: one np.asarray per
            # piece, up to 8 device round trips per checkpoint): the six
            # plane reductions concatenate into a single device array and
            # ride one device_get together with the identity arrays
            # (host-resident ones pass through untouched)
            sizes = [r.size for r in device_reds]
            if device_reds:
                fetch.append(jnp.concatenate(device_reds))
            host = jax.device_get(fetch)
            offsets = np.cumsum([0] + sizes)
            red_cat = host[-1] if device_reds else None
            h = hashlib.sha256()
            for kind, idx in layout:
                arr = (
                    host[idx]
                    if kind == "arr"
                    else red_cat[offsets[idx] : offsets[idx + 1]]
                )
                h.update(np.ascontiguousarray(arr))
            fp = self._key_fp = np.frombuffer(h.digest(), np.uint8)
        return fp

    def checkpoint(
        self, path: str, level: int,
        nreqs: int | None = None, threshold: float | None = None,
    ) -> None:
        """Persist the crawl state AFTER ``level`` completed: both servers'
        frontier states + liveness flags, the leader's path bookkeeping,
        the state LAYOUT (the planar Pallas engine and the interleaved
        XLA engine shape the frontier differently — collect.Frontier; a
        restore under the other engine converts), a key fingerprint, and —
        when called from :meth:`run` — the crawl parameters, so a resume
        under different keys/nreqs/threshold refuses instead of mixing
        pruning regimes.  Written atomically (tmp + rename) so an
        interruption mid-write never corrupts the previous checkpoint."""
        planar = collect._expand_engine()
        blob = {
            "level": np.int64(level),
            "radix": np.int64(self.radix),
            "planar": np.bool_(planar),
            "paths": self.paths,
            "n_nodes": np.int64(self.n_nodes),
            "last_counts": np.asarray(self._last_counts),
            "meta": np.array(
                [self.n_dims, self.data_len, self.f_max, self.min_bucket],
                np.int64,
            ),
            "key_fp": self._key_fingerprint(),
        }
        if nreqs is not None and threshold is not None:
            blob["params"] = np.array([float(nreqs), float(threshold)])
        for i, s in enumerate((self.server0, self.server1)):
            st = s.frontier.states
            blob[f"s{i}_seed"] = st.seed
            blob[f"s{i}_bit"] = st.bit
            blob[f"s{i}_y_bit"] = st.y_bit
            blob[f"s{i}_alive"] = s.frontier.alive
            blob[f"s{i}_alive_keys"] = s.alive_keys
        # ONE stacked fetch for both servers' state planes (was: one
        # np.asarray per plane, 10 device round trips per checkpoint);
        # host-resident entries pass through device_get untouched
        blob = jax.device_get(blob)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **blob)
        os.replace(tmp, path)
        self.obs.count("checkpoint_writes", level=level)
        obsmod.emit("checkpoint.write", path=path, level=level)

    def restore(
        self, path: str,
        nreqs: int | None = None, threshold: float | None = None,
    ) -> int:
        """Load a :meth:`checkpoint` and return the NEXT level to run.
        Refuses a checkpoint whose shape, key fingerprint, or (when both
        sides recorded them) crawl parameters differ from this Leader's —
        every mismatch would otherwise produce silently wrong hitters."""
        # materialize inside the context manager: NpzFile holds the file
        # descriptor open until closed, and run() later os.remove()s this
        # same path — a leaked handle pins the deleted file's blocks (and
        # on some filesystems fails the remove outright)
        with np.load(path) as npz:
            z = {k: npz[k] for k in npz.files}
        meta = z["meta"]
        want = [self.n_dims, self.data_len, self.f_max, self.min_bucket]
        if list(meta) != want:
            raise ValueError(
                f"checkpoint shape {list(meta)} != leader shape {want}"
            )
        # validate-before-mutate: a blob written under a different crawl
        # radix carries a frontier at a depth this leader's fused level
        # grid never visits — refuse with live state untouched (blobs
        # predating the radix stamp are radix-1 crawls)
        saved_radix = int(z["radix"]) if "radix" in z else 1
        if saved_radix != self.radix:
            raise ValueError(
                f"checkpoint crawl radix {saved_radix} != leader "
                f"crawl_radix_bits {self.radix}"
            )
        if "key_fp" not in z:
            raise ValueError(
                "checkpoint predates the key-fingerprint format — "
                "re-run the crawl from the start"
            )
        if not np.array_equal(z["key_fp"], self._key_fingerprint()):
            raise ValueError(
                "checkpoint was written under different key batches"
            )
        if "params" in z and nreqs is not None and threshold is not None:
            saved = z["params"]
            if saved[0] != float(nreqs) or saved[1] != float(threshold):
                raise ValueError(
                    f"checkpoint crawl params (nreqs, threshold) = "
                    f"({saved[0]:g}, {saved[1]:g}) != ({nreqs}, {threshold})"
                )
        saved_planar = bool(z["planar"])
        planar = collect._expand_engine()
        for i, s in enumerate((self.server0, self.server1)):
            states = EvalState(
                seed=jax.device_put(z[f"s{i}_seed"]),
                bit=jax.device_put(z[f"s{i}_bit"]),
                y_bit=jax.device_put(z[f"s{i}_y_bit"]),
            )
            if saved_planar != planar:
                states = _convert_layout(states, saved_planar)
            s.frontier = collect.Frontier(
                states=states, alive=jax.device_put(z[f"s{i}_alive"])
            )
            s.children = None
            s.alive_keys = z[f"s{i}_alive_keys"]
        self.paths = z["paths"]
        self.n_nodes = int(z["n_nodes"])
        self._last_counts = z["last_counts"]
        self._win = {}
        self._win_next = {}
        self.obs.count("checkpoint_restores", level=int(z["level"]))
        obsmod.emit("checkpoint.restore", path=path, level=int(z["level"]))
        lvl = int(z["level"])  # base bit level of the last completed round
        return lvl + min(self.radix, self.data_len - lvl)


def _convert_layout(states, from_planar: bool):
    """Frontier EvalState layout conversion for cross-engine checkpoint
    restore — delegates to :func:`collect.to_interleaved` /
    :func:`collect.to_planar`, the one source of truth for the engine-edge
    transposes.  Converting there and back is the identity."""
    return (
        collect.to_interleaved(states) if from_planar
        else collect.to_planar(states)
    )


def make_servers(
    keys0: IbDcfKeyBatch, keys1: IbDcfKeyBatch
) -> tuple[ServerState, ServerState]:
    n = keys0.cw_seed.shape[0]
    alive = np.ones(n, bool)
    return ServerState(keys0, alive.copy()), ServerState(keys1, alive.copy())
