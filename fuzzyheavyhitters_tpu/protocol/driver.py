"""In-process protocol driver: a leader and two colocated server states.

The correctness backbone of the framework — both servers' state machines run
in one process (the integration-test shape the reference intended with its
commented-out ``collect_test.rs``, SURVEY.md §4), with the trusted-exchange
data plane: the per-(node,client) packed share bits are compared directly
instead of passing through the GC+OT 2PC (functionally identical counts —
exactly what the leader reconstructs anyway via ``keep_values``,
ref: collect.rs:945-964 — with semi-honest security dropped).  The secure
data plane drops in behind the same ``counts_by_pattern`` seam.

Level-loop semantics mirror the reference leader (ref: leader.rs:185-297):

- threshold = ``max(1, threshold · nreqs)`` per level (leader.rs:193-194);
- ``data_len - 1`` inner levels then one last level (leader.rs:417-438);
- prune keeps only above-threshold children (leader.rs:229-234);
- paths decode MSB-first per dim; heavy hitters are the surviving leaves.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..ops.ibdcf import IbDcfKeyBatch
from . import collect


@dataclass
class ServerState:
    """One collector server's state (ref: server.rs:44-52 wraps the same)."""

    keys: IbDcfKeyBatch  # [N, d, 2]
    alive_keys: np.ndarray  # bool[N] liveness flags (ref: collect.rs:32)
    frontier: collect.Frontier | None = None
    children: object | None = None  # expand-time child-state cache


@dataclass
class CrawlResult:
    paths: np.ndarray  # bool[H, d, L] per-dim MSB-first paths
    counts: np.ndarray  # uint32[H]

    def decode_ints(self) -> np.ndarray:
        """paths -> int[H, d] leaf values (MSB-first per dim)."""
        L = self.paths.shape[-1]
        weights = 1 << np.arange(L - 1, -1, -1)
        return (self.paths.astype(np.int64) * weights).sum(-1)


@dataclass
class Leader:
    """Drives two ServerStates level by level (ref: leader.rs:185-297)."""

    server0: ServerState
    server1: ServerState
    n_dims: int
    data_len: int
    f_max: int = 256
    min_bucket: int = 1  # pin >1 only on compile-bound test hosts
    # leader-side bookkeeping
    paths: np.ndarray = field(default=None)  # bool[F, d, level]
    n_nodes: int = 0

    def tree_init(self):
        for s in (self.server0, self.server1):
            s.frontier = collect.tree_init(s.keys, self.min_bucket)
            s.children = None
        self.paths = np.zeros((1, self.n_dims, 0), bool)
        self.n_nodes = 1

    def run_level(self, level: int, nreqs: int, threshold: float) -> int:
        """One crawl->threshold->prune round; returns surviving node count.

        Trusted-exchange mode: counts are exact (the reconstruction
        ``v0 - v1`` of ref collect.rs:945-964, computed directly).
        """
        d = self.n_dims
        masks = collect.pattern_masks(d)
        p0, ch0 = collect.expand_share_bits(
            self.server0.keys, self.server0.frontier, level
        )
        p1, ch1 = collect.expand_share_bits(
            self.server1.keys, self.server1.frontier, level
        )
        self.server0.children, self.server1.children = ch0, ch1
        counts = collect.counts_by_pattern(
            p0,
            p1,
            masks,
            np.asarray(self.server0.alive_keys),
            self.server0.frontier.alive,
        )
        counts = np.asarray(counts)  # [F, 2^d]

        thresh = max(1, int(threshold * nreqs))  # ref: leader.rs:193-194
        keep = counts >= thresh  # [F, 2^d]
        keep[self.n_nodes :, :] = False
        parent, pattern, n_alive = collect.compact_survivors(
            keep, self.f_max, self.min_bucket
        )
        pat_bits = collect.pattern_to_bits(pattern, d)

        for s in (self.server0, self.server1):
            s.frontier = collect.advance_from_children(
                s.children, parent, pat_bits, n_alive
            )
            s.children = None

        # leader-side path bookkeeping (child bit j = (pattern >> j) & 1)
        new_paths = np.zeros((n_alive, d, self.paths.shape[-1] + 1), bool)
        for i in range(n_alive):
            new_paths[i, :, :-1] = self.paths[parent[i]]
            new_paths[i, :, -1] = pat_bits[i]
        self.paths = new_paths
        self.n_nodes = n_alive
        self._last_counts = counts[parent[:n_alive], pattern[:n_alive]]
        return n_alive

    def run(self, nreqs: int, threshold: float) -> CrawlResult:
        """Full crawl: init + data_len levels + final reconstruction
        (ref: leader.rs:417-438 then final_shares at :282-297)."""
        self.tree_init()
        for level in range(self.data_len):
            n = self.run_level(level, nreqs, threshold)
            if n == 0:
                return CrawlResult(
                    paths=np.zeros((0, self.n_dims, level + 1), bool),
                    counts=np.zeros(0, np.uint32),
                )
        return CrawlResult(paths=self.paths, counts=self._last_counts)


def make_servers(
    keys0: IbDcfKeyBatch, keys1: IbDcfKeyBatch
) -> tuple[ServerState, ServerState]:
    n = keys0.cw_seed.shape[0]
    alive = np.ones(n, bool)
    return ServerState(keys0, alive.copy()), ServerState(keys1, alive.copy())
