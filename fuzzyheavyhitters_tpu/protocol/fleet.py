"""Collector fleet: host-pair directory, placement, migration, failover.

The paper's protocol runs one collection against exactly TWO collector
servers (PAPER.md §0).  Everything below the leader already survives a
single restarted server (PR 3/4/8: reconnect replays, plane resets,
checkpoint re-seed), but a collection still dies with its host *pair*,
and a hot pair has no way to shed tenants.  This module adds the fleet
layer above the pair:

- :class:`FleetDirectory` — N collector host pairs register here (boot
  ids, capacity, per-session ``last_progress_s`` / stall-fill load
  signals sourced from each server's :class:`~.tenancy.TenantScheduler`
  via ``status``).  Registration is file-based so out-of-process
  servers can join: ``bin/server.py`` drops
  ``<FHH_FLEET>/<pair>_s<id>.json`` atomically at boot and ``scan()``
  folds the halves into pair rows.  In-process tests register pairs
  directly.
- :class:`FleetPlacer` — the leader-side scheduler.  ``place()`` puts a
  new collection on the least-loaded pair; ``migrate()`` moves a LIVE
  session between pairs mid-stream (quiesce at a window/level boundary,
  ``session_export`` on the source, ``session_import`` on the
  destination, journal replay for exactly-once ingest, ratchet replay
  for challenge identity — the heavy lifting lives in
  ``WindowedIngest.migrate``); ``failover()`` is the same machinery
  driven by a dead boot id on probe, importing the orphaned session's
  NEWEST checkpoint on a surviving pair.

Load model: a pair's load is ``placed / capacity`` plus the freshest
probed stall pressure (stall-fill ratio says the pair's device is
already timesharing; a stale ``last_progress_s`` says some tenant is
wedged and the pair is suspect).  Deliberately scalar — placement only
needs a total order, not a simulator.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import time

from ..obs import metrics
from ..obs import logs
from ..utils import guards


@dataclasses.dataclass
class HostPair:
    """One registered collector pair (two servers = one protocol unit)."""

    name: str
    host0: str = ""
    port0: int = 0
    host1: str = ""
    port1: int = 0
    boot0: str = ""
    boot1: str = ""
    capacity: int = 4
    alive: bool = True
    # freshest probed load signals (tenancy.TenantScheduler.stats + the
    # per-session last_progress age off the pair's status verb)
    stall_fill_ratio: float = 0.0
    max_progress_age_s: float = 0.0
    last_seen_s: float = 0.0

    def addr(self, which: int) -> tuple:
        return (self.host0, self.port0) if which == 0 else (self.host1, self.port1)


# static twin of the runtime guard map armed below (pyproject
# [tool.fhh-lint.guards] carries the same rows; the drift test in
# tests/test_concurrency.py pins all copies together)
_FLEET_GUARDS = {
    "_hosts": "_lock",
    "_placements": "_lock",
}


class FleetDirectory:
    """Registry of collector host pairs + session->pair placements.

    All mutable state lives behind one asyncio lock: the directory is
    read by the placer, the supervisor's probe loop, and status
    producers concurrently on the leader's event loop.
    """

    def __init__(self, fleet_dir: str | None = None, obs=None):
        self.fleet_dir = fleet_dir
        self.obs = obs
        self._hosts: dict = {}
        self._placements: dict = {}
        self._lock = asyncio.Lock()
        guards.install(self, _FLEET_GUARDS)

    # -- registration ------------------------------------------------------

    async def register(self, pair: HostPair) -> None:
        """Direct (in-process) registration; re-registering a name
        replaces the row — a restarted pair announces its new boot ids
        through the same door."""
        async with self._lock:
            pair.last_seen_s = time.time()
            self._hosts[pair.name] = pair

    async def scan(self) -> int:
        """Fold ``<pair>_s<id>.json`` registration files (written by
        bin/server.py under FHH_FLEET) into pair rows.  Returns the
        number of complete pairs registered.  Torn/partial files are
        skipped — registration writes are atomic (tmp+rename), so a
        skip only ever means "server still booting"."""
        if not self.fleet_dir:
            return 0
        halves: dict = {}
        try:
            names = sorted(os.listdir(self.fleet_dir))
        except OSError:
            return 0
        for fn in names:
            if not fn.endswith(".json") or "_s" not in fn:
                continue
            try:
                with open(os.path.join(self.fleet_dir, fn)) as f:
                    doc = json.load(f)
                pair = str(doc["pair"])
                sid = int(doc["server_id"])
            except (OSError, ValueError, KeyError):
                continue
            halves.setdefault(pair, {})[sid] = doc
        n = 0
        async with self._lock:
            for pair, by_id in sorted(halves.items()):
                if 0 not in by_id or 1 not in by_id:
                    continue
                d0, d1 = by_id[0], by_id[1]
                prev = self._hosts.get(pair)
                row = HostPair(
                    name=pair,
                    host0=str(d0.get("host", "")), port0=int(d0.get("port", 0)),
                    host1=str(d1.get("host", "")), port1=int(d1.get("port", 0)),
                    boot0=str(d0.get("boot_id", "")),
                    boot1=str(d1.get("boot_id", "")),
                    capacity=int(d0.get("capacity", 4)),
                    last_seen_s=time.time(),
                )
                if prev is not None:
                    row.stall_fill_ratio = prev.stall_fill_ratio
                    row.max_progress_age_s = prev.max_progress_age_s
                self._hosts[pair] = row
                n += 1
        return n

    # -- load signals ------------------------------------------------------

    async def note_load(self, name: str, *, stall_fill_ratio: float = 0.0,
                        max_progress_age_s: float = 0.0) -> None:
        """Record the freshest probed load signals for a pair
        (scheduler stall-fill ratio + the oldest session's
        ``last_progress`` age, both straight off the pair's ``status``)."""
        async with self._lock:
            row = self._hosts.get(name)
            if row is None:
                return
            row.stall_fill_ratio = float(stall_fill_ratio)
            row.max_progress_age_s = float(max_progress_age_s)
            row.last_seen_s = time.time()

    async def probe(self, probe_fn) -> list:
        """Run ``await probe_fn(pair) -> {"boot0", "boot1", ...}``
        against every live pair.  A raised exception, or a boot id that
        CHANGED since registration, marks the pair dead (the paper's
        protocol cannot continue a session against a restarted secure
        endpoint without the leader-side re-seed dance — fleet-level
        recovery treats both the same).  Returns the names newly marked
        dead, for the supervisor to fail their sessions over."""
        async with self._lock:
            live = [(p.name, p.boot0, p.boot1) for p in self._hosts.values()
                    if p.alive]
        died = []
        for name, boot0, boot1 in live:
            dead = False
            try:
                got = await probe_fn(name)
            # fhh-lint: disable=broad-except (a dead host fails its
            # probe in arbitrary ways — refused dial, timeout, torn
            # frame; EVERY failure mode means the same thing here:
            # mark the pair dead and fail its sessions over)
            except Exception:
                dead = True
            else:
                if boot0 and str(got.get("boot0", boot0)) != boot0:
                    dead = True
                if boot1 and str(got.get("boot1", boot1)) != boot1:
                    dead = True
            if dead:
                died.append(name)
        if died:
            async with self._lock:
                for name in died:
                    row = self._hosts.get(name)
                    if row is not None:
                        row.alive = False
            logs.emit("fleet.pairs_dead", pairs=sorted(died))
        return died

    async def mark_dead(self, name: str) -> None:
        async with self._lock:
            row = self._hosts.get(name)
            if row is not None:
                row.alive = False

    # -- placement ---------------------------------------------------------

    async def place(self, session: str, *, exclude: tuple = ()) -> HostPair:
        """Pick the least-loaded live pair for ``session`` and record
        the placement.  Load = placed/capacity, stall-fill ratio and
        stalled-progress age breaking ties (module doc)."""
        async with self._lock:
            placed: dict = {}
            for s, p in self._placements.items():
                placed[p] = placed.get(p, 0) + 1
            best, best_key = None, None
            for row in self._hosts.values():
                if not row.alive or row.name in exclude:
                    continue
                key = (
                    placed.get(row.name, 0) / max(1, row.capacity),
                    row.stall_fill_ratio,
                    row.max_progress_age_s,
                    row.name,
                )
                if best_key is None or key < best_key:
                    best, best_key = row, key
            if best is None:
                raise RuntimeError("fleet: no live pair to place onto")
            self._placements[session] = best.name
            return best

    async def placement_of(self, session: str) -> str | None:
        async with self._lock:
            return self._placements.get(session)

    async def move(self, session: str, name: str) -> None:
        async with self._lock:
            self._placements[session] = name

    async def release(self, session: str) -> None:
        async with self._lock:
            self._placements.pop(session, None)

    async def orphans_of(self, name: str) -> list:
        """Sessions placed on ``name`` (the dead pair's tenants, for the
        supervisor to re-place)."""
        async with self._lock:
            return sorted(s for s, p in self._placements.items() if p == name)

    async def pairs(self) -> list:
        async with self._lock:
            return [dataclasses.replace(p) for p in
                    sorted(self._hosts.values(), key=lambda r: r.name)]

    async def status(self) -> dict:
        async with self._lock:
            return {
                "pairs": {
                    p.name: {
                        "alive": p.alive,
                        "boot0": p.boot0,
                        "boot1": p.boot1,
                        "capacity": p.capacity,
                        "stall_fill_ratio": round(p.stall_fill_ratio, 6),
                        "max_progress_age_s": round(p.max_progress_age_s, 3),
                    }
                    for p in sorted(self._hosts.values(), key=lambda r: r.name)
                },
                "placements": dict(sorted(self._placements.items())),
            }


class FleetPlacer:
    """Leader-side scheduler over a :class:`FleetDirectory`.

    Owns the fleet observability: ``placement_decisions`` /
    ``session_migrations`` / ``session_failovers`` counters (the
    exporter auto-renders ``fhh_session_migrations_total``) and the
    ``migration_inflight_since`` gauge the stuck-migration alert rule
    watches (obs/alerts.py).  The migration/failover mechanics live in
    ``WindowedIngest.migrate`` / ``failover_to`` — the placer decides
    *where*, brackets the attempt for the alert rule, and keeps the
    directory's placements truthful.
    """

    def __init__(self, directory: FleetDirectory, obs=None):
        self.directory = directory
        self.obs = obs if obs is not None else metrics.Registry("fleet")

    async def place(self, session: str, *, exclude: tuple = ()) -> HostPair:
        pair = await self.directory.place(session, exclude=exclude)
        self.obs.count("placement_decisions")
        logs.emit("fleet.placed", session=session, pair=pair.name)
        return pair

    async def migrate(self, ingest, new_lead, *, session: str,
                      dest: str) -> dict:
        """Live-migrate ``ingest``'s session onto ``new_lead``'s pair.

        The inflight gauge stays set across the attempt so a wedged
        transfer trips the ``migration_stuck`` alert; it is cleared on
        BOTH outcomes (a failed migrate leaves the source authoritative
        — see WindowedIngest.migrate's ordering guarantee)."""
        self.obs.gauge("migration_inflight_since", time.time())
        try:
            stats = await ingest.migrate(new_lead)
        finally:
            self.obs.gauge("migration_inflight_since", 0.0)
        self.obs.count("session_migrations")
        self.obs.count("placement_decisions")
        await self.directory.move(session, dest)
        logs.emit("fleet.migrated", session=session, dest=dest, **stats)
        return stats

    async def failover(self, ingest, new_lead, *, session: str, dest: str,
                       level: int = -1) -> dict:
        """Recover an orphaned session (dead source pair) onto
        ``new_lead`` from its newest banked checkpoint."""
        self.obs.gauge("migration_inflight_since", time.time())
        try:
            stats = await ingest.failover_to(new_lead, level=level)
        finally:
            self.obs.gauge("migration_inflight_since", 0.0)
        self.obs.count("session_failovers")
        self.obs.count("placement_decisions")
        await self.directory.move(session, dest)
        logs.emit("fleet.failed_over", session=session, dest=dest, **stats)
        return stats

    async def recover_dead_pair(self, name: str, make_ingest, *,
                                level: int = -1) -> dict:
        """Supervisor hook: fail every session placed on dead pair
        ``name`` over to the least-loaded survivor.  ``make_ingest``
        maps ``(session, dest_pair) -> (ingest, new_lead)`` — the
        caller owns connection construction (tests pass in-process
        clients; production dials ``dest.addr(i)``)."""
        moved = {}
        for session in await self.directory.orphans_of(name):
            dest = await self.place(session, exclude=(name,))
            ingest, new_lead = await make_ingest(session, dest)
            moved[session] = await self.failover(
                ingest, new_lead, session=session, dest=dest.name,
                level=level)
        return moved

    def status(self) -> dict:
        return {
            "placement_decisions": int(
                self.obs.counter_value("placement_decisions")),
            "session_migrations": int(
                self.obs.counter_value("session_migrations")),
            "session_failovers": int(
                self.obs.counter_value("session_failovers")),
            "migration_inflight_since": float(
                self.obs.gauge_value("migration_inflight_since") or 0.0),
        }
