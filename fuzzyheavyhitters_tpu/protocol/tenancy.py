"""Tenant scheduling: interleave device work across collection sessions.

The ``pipeline_stalls`` telemetry (PR 5) names the idle device gaps a
single collection leaves: while one span's GC/OT exchange is on the
wire, the device sits idle.  With per-collection sessions
(protocol/sessions.py) a SECOND tenant's expand/kernel stage can fill
exactly those gaps — each session serializes its own verbs on its own
lock, so two sessions' verbs already interleave on the event loop; this
module makes that interleaving *scheduled* (FIFO-fair device turns) and
*observable* (stall-fill accounting):

- :class:`TenantScheduler` — ``device_turn(key)`` brackets a session's
  device-dispatch stage (one accelerator: turns serialize FIFO across
  sessions, so a tenant's dispatch burst cannot starve another's
  indefinitely — asyncio.Lock wakes waiters in acquisition order);
  ``wire_wait(key)`` brackets a session's data-plane waits.  A device
  turn taken while ANOTHER session is wire-waiting is a **stall fill**:
  the multi-tenant win, counted per server (``tenant_stall_fills`` /
  ``tenant_device_turns``) and surfaced through ``status``, the run
  report, and ``bench_multitenant``.
- :class:`WarmLadder` — the process-level registry of already-warmed
  compiled-program shapes.  jit executables are cached per process, so
  once ANY session warmed a (batch, bucket, path, layout) rung, a new
  collection on the same shape pays zero fresh compiles — the ladder
  makes warmup itself skip the redundant execution (warming runs real
  device programs; re-running them per tenant would cost seconds per
  rung for nothing).
"""

from __future__ import annotations

import asyncio
import contextlib
import time

from ..obs import trace as obstrace


class TenantScheduler:
    """FIFO device-turn scheduler + stall-fill accounting (module doc).

    All state mutates from the owning server's event loop only — the
    counters need no lock; the ``obs`` registry has its own."""

    def __init__(self, obs=None):
        self.obs = obs
        self._device_lock = asyncio.Lock()
        # session key -> depth of active wire waits (a session can hold
        # at most one at a time under its verb lock, but recovery paths
        # may nest; a count is the safe shape)
        self._wire: dict[str, int] = {}
        self.device_turns = 0
        self.stall_fills = 0
        self.turns_by_session: dict[str, int] = {}
        self.fills_by_session: dict[str, int] = {}
        # session key -> wall-clock of its last device turn: the age of
        # the OLDEST entry is the "is anything starving here" placement
        # signal the fleet layer reads (FleetDirectory.note_load)
        self.last_progress_s: dict[str, float] = {}

    # -- accounting primitives --------------------------------------------

    def _others_on_wire(self, key: str) -> bool:
        return any(n > 0 and k != key for k, n in self._wire.items())

    def _note_turn(self, key: str) -> None:
        self.device_turns += 1
        self.turns_by_session[key] = self.turns_by_session.get(key, 0) + 1
        self.last_progress_s[key] = time.time()
        if self.obs is not None:
            self.obs.count("tenant_device_turns")
        if self._others_on_wire(key):
            self.stall_fills += 1
            self.fills_by_session[key] = (
                self.fills_by_session.get(key, 0) + 1
            )
            if self.obs is not None:
                self.obs.count("tenant_stall_fills")

    # -- public API --------------------------------------------------------

    def device_turn(self, key: str, count: bool = True):
        """Async context manager bracketing one session's device-dispatch
        stage.  Turns serialize FIFO across sessions (one accelerator);
        acquiring while another session waits on the wire counts a
        stall fill.  ``count=False`` keeps the serialization but skips
        the accounting — the caller's dispatch already ran (and was
        counted) at frame arrival via :meth:`note_dispatch`, and
        double-counting would inflate the fill-ratio denominator."""
        return _DeviceTurn(self, key, count)

    @contextlib.contextmanager
    def wire_wait(self, key: str):
        """Sync context manager marking a session as blocked on the
        data plane (wraps the recv awaits in protocol/rpc.py)."""
        self._wire[key] = self._wire.get(key, 0) + 1
        # distributed trace: the wire wait is THE gap a second tenant's
        # device turn fills — record it as a child span of the active
        # verb so the merged timeline shows the stall being filled
        st = obstrace.span_begin() if obstrace.enabled() else None
        try:
            yield
        finally:
            if st is not None:
                obstrace.span_end(
                    st, "wire_wait",
                    self.obs.name if self.obs is not None else "server",
                )
            n = self._wire.get(key, 1) - 1
            if n <= 0:
                self._wire.pop(key, None)
            else:
                self._wire[key] = n

    def note_dispatch(self, key: str) -> None:
        """Lock-free turn accounting for dispatch sites that cannot
        await (the frame-arrival pre-expand runs outside any lock and
        must stay event-loop-atomic)."""
        self._note_turn(key)

    def wire_waiting(self) -> list:
        return sorted(k for k, n in self._wire.items() if n > 0)

    def forget(self, key: str) -> None:
        """Drop one session's accounting rows (retire / migration away):
        a dead tenant must not hold the pair's progress-age signal high
        forever."""
        self.turns_by_session.pop(key, None)
        self.fills_by_session.pop(key, None)
        self.last_progress_s.pop(key, None)

    def fleet_load(self, now: float | None = None) -> dict:
        """The pair-half's placement signals, in exactly the shape
        :meth:`FleetDirectory.note_load` consumes: the stall-fill ratio
        (how contended this accelerator is) and the age of the
        least-recently-progressing session (is anything starving)."""
        if now is None:
            now = time.time()
        ages = [now - t for t in self.last_progress_s.values()]
        return {
            "stall_fill_ratio": round(
                self.stall_fills / max(1, self.device_turns), 6
            ),
            "max_progress_age_s": round(max(ages, default=0.0), 3),
        }

    def stats(self) -> dict:
        return {
            "device_turns": self.device_turns,
            "stall_fills": self.stall_fills,
            "fill_ratio": round(
                self.stall_fills / max(1, self.device_turns), 6
            ),
            "turns_by_session": dict(sorted(self.turns_by_session.items())),
            "fills_by_session": dict(sorted(self.fills_by_session.items())),
            "wire_waiting": self.wire_waiting(),
        }


class _DeviceTurn:
    __slots__ = ("_sched", "_key", "_count", "_trace")

    def __init__(self, sched: TenantScheduler, key: str, count: bool = True):
        self._sched = sched
        self._key = key
        self._count = count
        self._trace = None

    async def __aenter__(self):
        # the span covers lock wait + dispatch: a long device_turn with
        # a short dispatch IS the cross-tenant queueing the scheduler
        # exists to make visible
        self._trace = obstrace.span_begin() if obstrace.enabled() else None
        await self._sched._device_lock.acquire()
        if self._count:
            self._sched._note_turn(self._key)
        return self

    async def __aexit__(self, exc_type, exc, tb):
        self._sched._device_lock.release()
        if self._trace is not None:
            obs = self._sched.obs
            obstrace.span_end(
                self._trace, "device_turn",
                obs.name if obs is not None else "server",
                error=exc_type is not None,
            )
        return False


# ---------------------------------------------------------------------------
# Warm ladder: process-level warmed-shape registry
# ---------------------------------------------------------------------------

# keys are tuples built by rpc._warm_bucket from everything that feeds a
# compiled program's identity (batch shapes, bucket, field ladder, ot
# path, engine layout, mesh/kernel shard plan).  Process-level on
# purpose: the jit executable cache is process-level, so two sessions —
# or two in-process servers, as in the bench and the tests — genuinely
# share the compiled programs the ladder tracks.
_WARMED: set = set()  # fhh-guard: _WARMED=_WARM_LOCK


# single event loop in production, but tests may probe from threads;
# a plain mutex keeps the set consistent either way
import threading as _threading  # noqa: E402

_WARM_LOCK = _threading.Lock()


def warmed(key: tuple) -> bool:
    """True when some session in this process already warmed ``key``
    (its compiled programs are in the process jit cache)."""
    with _WARM_LOCK:
        return key in _WARMED


def mark_warmed(key: tuple) -> None:
    with _WARM_LOCK:
        _WARMED.add(key)


def ladder_size() -> int:
    with _WARM_LOCK:
        return len(_WARMED)


def ladder_reset() -> None:
    """Test hook: forget every warmed shape (does NOT clear the jit
    cache — only the skip bookkeeping)."""
    with _WARM_LOCK:
        _WARMED.clear()
