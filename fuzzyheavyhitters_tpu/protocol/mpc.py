"""Beaver-triple multiplication checks, batched over clients.

Re-derivation of the reference's commented-out MPC verification layer
(ref: src/mpc.rs:14-223, 246-322 — ``TripleShare``, ``MulState`` with its
``cor_share -> cor -> out_share -> verify`` two-round protocol, and the
``ManyMulState`` batch wrapper).  The TPU-native shape: a whole batch of
clients' states is a handful of field tensors, every step one fused device
program; the two communication rounds (cor exchange, out-share exchange)
are the protocol seams the caller routes over its transport — the
data-plane socket in protocol/rpc.py, or ``psum``-style collectives on a
2-chip mesh.

We compute, in MPC over additive shares (share0 + share1 = value):

    out = sum_i  r_i * [ x_i * y_i + z_i ]        (i over CHECKS checks)

which is zero for honest inputs.  With Beaver triple (a, b, c = a*b):
``d = x - a`` and ``e = y - b`` are opened (the cor round), then

    [x*y + z] = d*e + d*b + e*a + c + z

where ``d*e`` is added by one server only (mpc.rs:188-196: server_idx
true adds it).  The random coefficients r_i come from the servers' shared
randomness so a cheater cannot anticipate them.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import prg

CHECKS = 3  # TRIPLES_PER_LEVEL (ref: sketch.rs:6)


class TripleBatch(NamedTuple):
    """One party's additive shares of Beaver triples, any batch shape."""

    a: jax.Array
    b: jax.Array
    c: jax.Array


def level_slab(triples: TripleBatch, level: int) -> TripleBatch:
    """One level's Beaver-triple slab out of a ``[..., L-1, CHECKS]``
    batch.  The slab partition is the one-shot unit of the restartable
    sketch (protocol/sketch.py ratchet): each level's checks consume
    exactly its own slab, and a recovered level re-opens the SAME slab
    under the SAME ratcheted challenge — a bit-identical replay, never a
    second opening under fresh randomness."""
    return TripleBatch(*[a[..., level, :] for a in triples])


class MulStateBatch(NamedTuple):
    """One party's inputs to a batch of multiplication checks.

    All leaves are field tensors [..., CHECKS(, limbs)]."""

    xs: jax.Array
    ys: jax.Array
    zs: jax.Array
    rs: jax.Array
    triples: TripleBatch


def gen_triples(field, shape, seed) -> tuple[TripleBatch, TripleBatch]:
    """Both parties' triple shares for ``shape`` checks (ref: mpc.rs:18-45).

    Client-side (the reference has clients supply triples inside their
    sketch keys, sketch.rs:113-127; the trust model is identical:
    semi-honest servers, malicious clients caught by the sketch
    relations)."""
    w = 8 if field.limb_shape else 4
    n = int(np.prod(shape))
    words = prg.stream_words(jnp.asarray(seed, jnp.uint32), 5 * n * w)
    words = words.reshape((5, n, w))
    full = tuple(shape) + field.limb_shape
    a = field.sample(words[0]).reshape(full)
    b = field.sample(words[1]).reshape(full)
    c = field.mul(a, b)
    a0 = field.sample(words[2]).reshape(full)
    b0 = field.sample(words[3]).reshape(full)
    c0 = field.sample(words[4]).reshape(full)
    return (
        TripleBatch(a=a0, b=b0, c=c0),
        TripleBatch(a=field.sub(a, a0), b=field.sub(b, b0), c=field.sub(c, c0)),
    )


@partial(jax.jit, static_argnames=("field",))
def cor_share(field, state: MulStateBatch):
    """(ds, es) shares to open: d = x - a, e = y - b (mpc.rs:143-159)."""
    return field.sub(state.xs, state.triples.a), field.sub(state.ys, state.triples.b)


@partial(jax.jit, static_argnames=("field",))
def cor(field, share0, share1):
    """Combine both parties' cor shares into the opened (d, e)
    (mpc.rs:162-181)."""
    d0, e0 = share0
    d1, e1 = share1
    return field.add(d0, d1), field.add(e0, e1)


@partial(jax.jit, static_argnames=("field", "server_idx"))
def out_share(field, server_idx: bool, state: MulStateBatch, opened):
    """This party's share of out = sum_i r_i*[x_i*y_i + z_i]
    (mpc.rs:184-216).  ``d*e`` is added by server 1 only."""
    d, e = opened
    term = field.add(field.mul(d, state.triples.b), field.mul(e, state.triples.a))
    term = field.add(term, state.triples.c)
    term = field.add(term, state.zs)
    if server_idx:
        term = field.add(term, field.mul(d, e))
    term = field.mul(term, state.rs)
    return field.sum(term, axis=term.ndim - 1 - len(field.limb_shape))


@partial(jax.jit, static_argnames=("field",))
def verify(field, out0, out1) -> jax.Array:
    """bool[...]: True where the check batch passes (sum of out shares is
    zero, mpc.rs:218-223)."""
    total = field.canon(field.add(out0, out1))
    if field.limb_shape:
        return ~jnp.any(total != 0, axis=-1)
    return total == 0
