// Streaming reservoir sampler — the native data-loader core, plus the
// incremental in-memory reservoir the ingest front door sheds into.
//
// The reference's samplers are native Rust over multi-GB CSVs: a memmap
// re-read with row indexing (src/sample_covid_data.rs:75-135) and a seeded
// reservoir (src/sample_covid_data.rs:158-166, sample_driving_data.rs:72-97).
// This is the TPU framework's equivalent: one streaming pass, O(k) memory,
// quoted-field-aware splitting of just the two requested columns, and a
// seeded xoshiro256** reservoir (algorithm R) so runs are reproducible.
//
// Exposed as a C ABI for ctypes (no pybind11 in this toolchain):
//
//   long csv_reservoir_sample(path, col_a, col_b, k, seed, out_a, out_b)
//     -> number of rows sampled (<= k), or -1 on open failure.
//
//   // incremental reservoir (resilience/admission.py's shed mode): the
//   // caller owns the slot table of payloads, the reservoir only decides
//   // slot placement — offer n items, get back each item's slot in
//   // [0, k) (replace the occupant) or -1 (shed this item).  State is
//   // fully extractable/restorable so a recovering server resumes the
//   // SAME sampling stream (checkpoint-carried, seed-reproducible).
//   void *reservoir_new(long k, unsigned long long seed);
//   long  reservoir_offer(void *r, long n, long *out_slots);  // -> kept
//   void  reservoir_state(void *r, unsigned long long out[6]);
//   void *reservoir_from_state(const unsigned long long st[6]);
//   void  reservoir_free(void *r);
//
// Build: g++ -O3 -shared -fPIC reservoir.cc -o libreservoir.so
// (fuzzyheavyhitters_tpu/native/__init__.py does this on first use).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Xoshiro256 {
  uint64_t s[4];
  explicit Xoshiro256(uint64_t seed) {
    // splitmix64 expansion of the seed into the state
    uint64_t x = seed;
    for (auto &w : s) {
      x += 0x9E3779B97F4A7C15ULL;
      uint64_t z = x;
      z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
      z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
      w = z ^ (z >> 31);
    }
  }
  static uint64_t rotl(uint64_t v, int k) { return (v << k) | (v >> (64 - k)); }
  uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
  // uniform in [0, n) without modulo bias (Lemire)
  uint64_t below(uint64_t n) {
    __uint128_t m = (__uint128_t)next() * n;
    uint64_t lo = (uint64_t)m;
    if (lo < n) {
      uint64_t floor = (~n + 1) % n;
      while (lo < floor) {
        m = (__uint128_t)next() * n;
        lo = (uint64_t)m;
      }
    }
    return (uint64_t)(m >> 64);
  }
};

// Extract fields col_a and col_b from one CSV line (RFC-4180-ish: quoted
// fields may contain commas; doubled quotes inside quotes are fine for
// numeric columns, which is all we parse).  Returns true when both parse.
bool parse_cols(const char *line, int col_a, int col_b, double *a, double *b) {
  int col = 0, want = 2;
  const char *p = line;
  const char *field_start = p;
  bool in_quotes = false;
  double *dst;
  while (true) {
    char c = *p;
    if (in_quotes) {
      if (c == '"') in_quotes = false;
      else if (c == '\0') return false;
      ++p;
      continue;
    }
    if (c == '"') {
      in_quotes = true;
      ++p;
      continue;
    }
    if (c == ',' || c == '\0' || c == '\n' || c == '\r') {
      dst = (col == col_a) ? a : (col == col_b) ? b : nullptr;
      if (dst != nullptr) {
        const char *fs = field_start;
        if (*fs == '"') ++fs;  // numeric field wrapped in quotes
        char *end = nullptr;
        *dst = strtod(fs, &end);
        if (end == fs) return false;  // empty / non-numeric field
        if (--want == 0) return true;
      }
      if (c != ',') return false;  // line ended before both columns
      ++col;
      field_start = ++p;
      continue;
    }
    ++p;
  }
}

// Incremental algorithm-R reservoir over caller-owned slots.  Identical
// math to the CSV path (same RNG, same below()), factored so the ingest
// plane can shed admissions one submission at a time.
struct Reservoir {
  Xoshiro256 rng;
  long k;
  long seen;
  explicit Reservoir(long k_, uint64_t seed) : rng(seed), k(k_), seen(0) {}
};

}  // namespace

extern "C" {

void *reservoir_new(long k, unsigned long long seed) {
  if (k <= 0) return nullptr;
  return new Reservoir(k, seed);
}

void reservoir_free(void *r) { delete static_cast<Reservoir *>(r); }

// Offer n sequential items; out_slots[i] = slot in [0, k) the i-th item
// lands in (replacing the occupant), or -1 when it is shed.  Returns the
// number of items kept.
long reservoir_offer(void *rp, long n, long *out_slots) {
  Reservoir *r = static_cast<Reservoir *>(rp);
  long kept = 0;
  for (long i = 0; i < n; ++i) {
    long slot;
    if (r->seen < r->k) {
      slot = r->seen;  // fill phase: sequential slots
    } else {
      uint64_t j = r->rng.below((uint64_t)r->seen + 1);
      slot = ((long)j < r->k) ? (long)j : -1;
    }
    out_slots[i] = slot;
    if (slot >= 0) ++kept;
    ++r->seen;
  }
  return kept;
}

// State layout: [k, seen, s0, s1, s2, s3] — enough to resume the exact
// sampling stream after a checkpoint restore.
void reservoir_state(void *rp, unsigned long long out[6]) {
  Reservoir *r = static_cast<Reservoir *>(rp);
  out[0] = (unsigned long long)r->k;
  out[1] = (unsigned long long)r->seen;
  for (int i = 0; i < 4; ++i) out[2 + i] = r->rng.s[i];
}

void *reservoir_from_state(const unsigned long long st[6]) {
  if ((long)st[0] <= 0) return nullptr;
  Reservoir *r = new Reservoir((long)st[0], 0);
  r->seen = (long)st[1];
  for (int i = 0; i < 4; ++i) r->rng.s[i] = st[2 + i];
  return r;
}

long csv_reservoir_sample(const char *path, int col_a, int col_b, long k,
                          unsigned long long seed, double *out_a,
                          double *out_b) {
  FILE *f = fopen(path, "rb");
  if (f == nullptr) return -1;
  setvbuf(f, nullptr, _IOFBF, 1 << 20);
  Xoshiro256 rng(seed);
  std::string line;
  line.reserve(4096);
  char buf[1 << 16];
  long seen = 0, kept = 0;
  bool header = true;
  while (fgets(buf, sizeof buf, f) != nullptr) {
    line += buf;
    if (!line.empty() && line.back() != '\n' && !feof(f)) continue;  // long line
    if (header) {  // skip the header row (both reference samplers do)
      header = false;
      line.clear();
      continue;
    }
    double a, b;
    if (parse_cols(line.c_str(), col_a, col_b, &a, &b)) {
      if (kept < k) {
        out_a[kept] = a;
        out_b[kept] = b;
        ++kept;
      } else {
        uint64_t j = rng.below((uint64_t)seen + 1);
        if ((long)j < k) {
          out_a[j] = a;
          out_b[j] = b;
        }
      }
      ++seen;
    }
    line.clear();
  }
  fclose(f);
  return kept;
}

}  // extern "C"
