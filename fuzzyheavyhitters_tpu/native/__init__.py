"""Native runtime components (C++ via ctypes — no pybind11 in this image).

The reference's data loaders are native Rust streaming multi-GB CSVs
(memmap row indexing + seeded reservoir, src/sample_covid_data.rs:75-166,
src/sample_driving_data.rs:72-97).  This package holds their C++
equivalents, compiled lazily with the system ``g++`` on first use and
cached next to the source; every caller has a pure-NumPy fallback, so the
framework stays importable where no toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "reservoir.cc")
_LIB = os.path.join(_DIR, "libreservoir.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _load():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        ):
            # build to a private temp path and rename into place: the
            # rename is atomic, so concurrent builders never dlopen a
            # half-written artifact and long-running processes keep their
            # already-mapped inode (truncating in place could SIGBUS them)
            tmp = f"{_LIB}.build.{os.getpid()}"
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", tmp],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, _LIB)
            except (OSError, subprocess.SubprocessError):
                # no g++ / compile error / timeout: the NumPy fallback
                # serves every caller — anything else should surface
                _build_failed = True
                return None
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        try:
            lib = ctypes.CDLL(_LIB)
            lib.csv_reservoir_sample.restype = ctypes.c_long
            lib.csv_reservoir_sample.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_long,
                ctypes.c_ulonglong,
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ]
            _lib = lib
        except OSError:
            _build_failed = True
    return _lib


def available() -> bool:
    """True when the native loader is (or can be) built and loaded."""
    return _load() is not None


def csv_reservoir_sample(
    path: str, col_a: int, col_b: int, k: int, seed: int
) -> np.ndarray | None:
    """Reservoir-sample ``k`` rows' (col_a, col_b) floats from a CSV in one
    streaming pass with O(k) memory.  Returns float64[kept, 2], or None when
    the native library is unavailable (callers fall back to NumPy)."""
    lib = _load()
    if lib is None:
        return None
    out_a = np.empty(k, np.float64)
    out_b = np.empty(k, np.float64)
    kept = lib.csv_reservoir_sample(
        path.encode(), col_a, col_b, k, seed & (2**64 - 1), out_a, out_b
    )
    if kept < 0:
        raise FileNotFoundError(path)
    return np.stack([out_a[:kept], out_b[:kept]], axis=1)
