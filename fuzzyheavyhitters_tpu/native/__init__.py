"""Native runtime components (C++ via ctypes — no pybind11 in this image).

The reference's data loaders are native Rust streaming multi-GB CSVs
(memmap row indexing + seeded reservoir, src/sample_covid_data.rs:75-166,
src/sample_driving_data.rs:72-97).  This package holds their C++
equivalents, compiled lazily with the system ``g++`` on first use and
cached next to the source; every caller has a pure-NumPy fallback, so the
framework stays importable where no toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "reservoir.cc")
_LIB = os.path.join(_DIR, "libreservoir.so")
_lock = threading.Lock()
_lib = None  # fhh-guard: _lib=_lock
_build_failed = False  # fhh-guard: _build_failed=_lock


def _load():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        ):
            # build to a private temp path and rename into place: the
            # rename is atomic, so concurrent builders never dlopen a
            # half-written artifact and long-running processes keep their
            # already-mapped inode (truncating in place could SIGBUS them)
            tmp = f"{_LIB}.build.{os.getpid()}"
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", tmp],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, _LIB)
            except (OSError, subprocess.SubprocessError):
                # no g++ / compile error / timeout: the NumPy fallback
                # serves every caller — anything else should surface
                _build_failed = True
                return None
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        try:
            lib = ctypes.CDLL(_LIB)
            lib.csv_reservoir_sample.restype = ctypes.c_long
            lib.csv_reservoir_sample.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_long,
                ctypes.c_ulonglong,
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ]
            lib.reservoir_new.restype = ctypes.c_void_p
            lib.reservoir_new.argtypes = [ctypes.c_long, ctypes.c_ulonglong]
            lib.reservoir_free.argtypes = [ctypes.c_void_p]
            lib.reservoir_offer.restype = ctypes.c_long
            lib.reservoir_offer.argtypes = [
                ctypes.c_void_p, ctypes.c_long,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ]
            lib.reservoir_state.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
            ]
            lib.reservoir_from_state.restype = ctypes.c_void_p
            lib.reservoir_from_state.argtypes = [
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
            ]
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError: a stale .so from before the incremental
            # reservoir ABI — treat like no native library at all (the
            # pure-Python twin below is bit-identical)
            _build_failed = True
        return _lib


def available() -> bool:
    """True when the native loader is (or can be) built and loaded."""
    return _load() is not None


def csv_reservoir_sample(
    path: str, col_a: int, col_b: int, k: int, seed: int
) -> np.ndarray | None:
    """Reservoir-sample ``k`` rows' (col_a, col_b) floats from a CSV in one
    streaming pass with O(k) memory.  Returns float64[kept, 2], or None when
    the native library is unavailable (callers fall back to NumPy)."""
    lib = _load()
    if lib is None:
        return None
    out_a = np.empty(k, np.float64)
    out_b = np.empty(k, np.float64)
    kept = lib.csv_reservoir_sample(
        path.encode(), col_a, col_b, k, seed & (2**64 - 1), out_a, out_b
    )
    if kept < 0:
        raise FileNotFoundError(path)
    return np.stack([out_a[:kept], out_b[:kept]], axis=1)


# ---------------------------------------------------------------------------
# Incremental in-memory reservoir (the ingest front door's shed mode)
# ---------------------------------------------------------------------------

_U64 = (1 << 64) - 1


class _PyXoshiro256:
    """Pure-Python twin of reservoir.cc's Xoshiro256 (splitmix64 seeding,
    xoshiro256**, Lemire unbiased bounding) — BIT-IDENTICAL by
    construction, so a reservoir sampled without the native library (or
    restored on a host without g++) makes the same slot decisions."""

    __slots__ = ("s",)

    def __init__(self, seed: int):
        x = seed & _U64
        s = []
        for _ in range(4):
            x = (x + 0x9E3779B97F4A7C15) & _U64
            z = x
            z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _U64
            z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _U64
            s.append(z ^ (z >> 31))
        self.s = s

    @staticmethod
    def _rotl(v: int, k: int) -> int:
        return ((v << k) | (v >> (64 - k))) & _U64

    def next(self) -> int:
        s = self.s
        result = (self._rotl((s[1] * 5) & _U64, 7) * 9) & _U64
        t = (s[1] << 17) & _U64
        s[2] ^= s[0]
        s[3] ^= s[1]
        s[1] ^= s[2]
        s[0] ^= s[3]
        s[2] ^= t
        s[3] = self._rotl(s[3], 45)
        return result

    def below(self, n: int) -> int:
        m = self.next() * n
        lo = m & _U64
        if lo < n:
            floor = ((_U64 + 1) - n) % n
            while lo < floor:
                m = self.next() * n
                lo = m & _U64
        return m >> 64


class Reservoir:
    """Seeded algorithm-R reservoir over CALLER-OWNED slots: ``offer(n)``
    returns each sequential item's slot in ``[0, k)`` (replace the
    occupant) or ``-1`` (shed the item).  Runs on the native library when
    available, the bit-identical Python twin otherwise; ``state()`` /
    ``from_state()`` round-trip the full sampling stream so a restored
    server continues the SAME (seed-reproducible) shed sequence."""

    def __init__(self, k: int, seed: int, *, _handle=None, _py=None,
                 _seen: int = 0):
        if k <= 0:
            raise ValueError("reservoir capacity must be positive")
        self.k = int(k)
        self._lib = _load()
        if _handle is not None or _py is not None:
            self._handle, self._py, self._seen = _handle, _py, _seen
            return
        if self._lib is not None:
            self._handle = self._lib.reservoir_new(self.k, seed & _U64)
            self._py = None
        else:
            self._handle = None
            self._py = _PyXoshiro256(seed)
        self._seen = 0

    @property
    def seen(self) -> int:
        return self._seen

    def offer(self, n: int = 1) -> np.ndarray:
        """Slots for the next ``n`` sequential items (int64[n]; -1 = shed)."""
        if n <= 0:
            return np.zeros(0, np.int64)
        if self._handle is not None:
            out = np.empty(n, np.int64)
            self._lib.reservoir_offer(self._handle, n, out)
            self._seen += n
            return out
        out = np.empty(n, np.int64)
        for i in range(n):
            if self._seen < self.k:
                out[i] = self._seen
            else:
                j = self._py.below(self._seen + 1)
                out[i] = j if j < self.k else -1
            self._seen += 1
        return out

    def state(self) -> np.ndarray:
        """uint64[6]: [k, seen, s0..s3] — checkpointable."""
        if self._handle is not None:
            out = np.empty(6, np.uint64)
            self._lib.reservoir_state(self._handle, out)
            return out
        return np.array(
            [self.k, self._seen] + list(self._py.s), np.uint64
        )

    @classmethod
    def from_state(cls, st) -> "Reservoir":
        st = np.ascontiguousarray(np.asarray(st, np.uint64))
        if st.shape != (6,):
            raise ValueError("reservoir state must be uint64[6]")
        k, seen = int(st[0]), int(st[1])
        lib = _load()
        if lib is not None:
            handle = lib.reservoir_from_state(st)
            return cls(k, 0, _handle=handle, _seen=seen)
        py = _PyXoshiro256(0)
        py.s = [int(v) for v in st[2:]]
        return cls(k, 0, _py=py, _seen=seen)

    def __del__(self):
        lib, handle = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and handle is not None:
            lib.reservoir_free(handle)
