"""End-of-run machine-readable report.

One JSON document aggregating every registry's snapshot — the artifact
bench.py and postmortems consume instead of scraping stdout.  Schema
(``fhh-run-report/1``)::

    {
      "schema": "fhh-run-report/1",
      "written_at": <epoch seconds>,
      "registries": {
        "server0": {
          "counters": {"data_bytes_sent": {"total": N, "by_level": {"0": n0, ...}}, ...},
          "gauges":   {"survivors":       {"last": v, "by_level": {...}}, ...},
          "phases":   {"fss": {"seconds": s, "count": c, "by_level": {...}}, ...}
        },
        ...
      }
    }

Well-known metric names (what populates them):

- phases ``fss`` / ``gc_ot`` / ``field`` — the reference's per-level
  3-phase server taxonomy (protocol/rpc.py crawl verbs; trusted mode's
  ``gc_ot`` slot is the plaintext exchange), plus ``level`` on the
  leader/driver side and ``upload_keys`` / ``setup`` one-offs.
- phases ``otext`` / ``garble`` / ``eval`` / ``b2a`` — the secure-kernel
  split of ``gc_ot`` (extension, circuit garble/eval — zero on the
  1-of-2^S path — and payload-table/open + field conversion); they are
  a BREAKDOWN of gc_ot, not additive with it, and the wire wait is the
  gc_ot remainder.  Counters ``ot_path_ot2s`` / ``ot_path_gc`` count
  levels by the equality-test engine taken.  Rolled up across
  registries into a top-level ``secure_kernels`` section whenever a
  secure crawl ran.
- counters ``data_bytes_sent`` / ``data_bytes_recv`` /
  ``data_msgs_sent`` — server↔server data plane, per level;
  ``control_bytes_*`` — leader↔server control plane;
  ``device_fetches`` — device->host transfers (the floor for
  remote-chip tunnels: fetch COUNT, not byte count — now both are
  measured); ``gc_tests`` — secure-mode equality tests;
  ``checkpoint_writes`` / ``checkpoint_restores``.
- gauges ``ot_batch_size`` (per level), ``survivors`` /
  ``frontier_nodes`` (per level).
- counters ``recoveries`` / ``levels_rerun`` / ``shards_rerun``
  (supervising leaders, socket and mesh) and ``dedup_hits`` /
  ``verb_requests`` (servers' idempotent-replay accounting) — rolled up
  across registries into a top-level ``recovery`` section
  (``{count, levels_rerun, shards_rerun, dedup_hits, dedup_hit_rate}``)
  whenever any supervised component ran, so a recovered run is
  distinguishable from a fault-free one in the report alone.
- gauge ``data_shards`` + phase ``ici_reduce`` + counters
  ``mesh_reshards`` / ``mesh_faults`` (multi-chip servers,
  parallel/server_mesh.py: client-axis shard count, the pre-wire ICI
  psum's fetch-synced seconds, and device-loss recovery events), plus
  gauge ``kernel_shards`` + phase ``kernel_gather`` / counter
  ``kernel_gathers`` (the row-sharded secure kernel stage,
  parallel/kernel_shard.py: the level's active kernel shard count — 1 =
  the degraded gather-to-one-device path — and that gather's dispatch
  seconds, ~0 whenever the sharded stage carries the crawl) — rolled
  up into a top-level ``mesh`` section whenever a multi-chip crawl ran.
- counters ``ingest_admitted`` / ``ingest_shed`` / ``ingest_rejected`` /
  ``ingest_windows`` + phases ``ingest`` / ``window_crawl`` (the
  windowed front-door driver's dedicated registry,
  leader_rpc.WindowedIngest) — rolled up into a top-level ``ingest``
  section (``{admitted, shed, rejected, windows, keys_per_sec,
  window_crawl_seconds}``) whenever a streaming run happened; servers
  additionally keep ``pool_*`` counters surfaced by the ``status`` verb.

``FHH_RUN_REPORT=<path>`` makes the binaries (and bench) write the
report there at exit / on SIGTERM; :func:`maybe_write_run_report` is
that one-liner.
"""

from __future__ import annotations

import contextlib
import json
import os
import signal
import time

from . import alerts as _alerts
from . import metrics
from ..utils import taint_guard
from . import trace as tracemod
from .hist import Histogram

SCHEMA = "fhh-run-report/1"


def run_report(registries=None) -> dict:
    """Aggregate snapshot of ``registries`` (default: every live one,
    plus the retained final snapshots of dropped ones — see
    ``metrics._retain_final``; snapshots beyond the retention bound are
    counted under ``dropped_registries`` so the cap is never silent).

    Same-named registries (a second ``driver.Leader`` after a checkpoint
    restore registers another ``driver``) get deterministic ``name#2``,
    ``name#3``, ... keys in registration order instead of silently
    overwriting each other."""
    dropped = 0
    if registries is None:
        # dedupe by (name, seq), live snapshot winning: at interpreter
        # exit the weakref finalizers (whose exitfunc registers at first
        # Registry creation, AFTER e.g. bench's atexit dump) may have
        # already retained final snapshots of registries that are still
        # alive — without the dedupe every one would appear twice
        by_id = {
            (name, seq): (name, seq, snap)
            for name, seq, snap in metrics.final_snapshots()
        }
        for r in metrics.all_registries():
            by_id[(r.name, r.seq)] = (r.name, r.seq, r.report())
        items = sorted(by_id.values(), key=lambda t: (t[0], t[1]))
        dropped = metrics.final_dropped()
    else:
        items = [(r.name, r.seq, r.report()) for r in registries]
    out: dict = {}
    seen: dict = {}
    for name, _seq, snap in items:
        n = seen[name] = seen.get(name, 0) + 1
        out[name if n == 1 else f"{name}#{n}"] = snap
    doc = {
        "schema": SCHEMA,
        "written_at": round(time.time(), 3),
        "registries": out,
    }
    rec = _recovery_summary(out)
    if rec is not None:
        doc["recovery"] = rec
    pipe = _pipeline_summary(out)
    if pipe is not None:
        doc["pipeline"] = pipe
    sk = _secure_kernel_summary(out)
    if sk is not None:
        doc["secure_kernels"] = sk
    sketch = _sketch_summary(out)
    if sketch is not None:
        doc["sketch"] = sketch
    ing = _ingest_summary(out)
    if ing is not None:
        doc["ingest"] = ing
    fleet = _fleet_summary(out)
    if fleet is not None:
        doc["fleet"] = fleet
    mesh = _mesh_summary(out)
    if mesh is not None:
        doc["mesh"] = mesh
    sess = _sessions_summary(out)
    if sess is not None:
        doc["sessions"] = sess
    slo = _slo_summary(out)
    if slo is not None:
        doc["slo"] = slo
    # alert transitions (obs.alerts): everything that fired in this
    # process, so a postmortem reader sees the stall/burn/backlog
    # events inline with the accounting they explain — None (absent)
    # when nothing fired, keeping the pre-alert report shape exact
    al = _alerts.report_section()
    if al is not None:
        doc["alerts"] = al
    if dropped:
        doc["dropped_registries"] = dropped
    # the report is handed to files/stdout whole: assert no registered
    # secret buffer rode a summary row in (fhh-taint runtime twin)
    taint_guard.check(doc, sink="run-report")
    return doc


def _slo_summary(registries: dict) -> dict | None:
    """Cross-registry SLO rollup: every latency histogram
    (obs.hist.Histogram — fixed buckets, so same-named histograms merge
    across the leader, both servers, and every per-session registry by
    summing bucket counts) reduced to p50/p95/p99 + max.  Per-verb RPC
    latencies (``rpc:<verb>`` histograms on the servers) fold into a
    ``verbs`` sub-table; everything else (``level_latency``,
    ``seal_to_hitters``, ``ingest_admit``) is a top-level metric with a
    ``by_registry`` breakdown so the merged count's multiplicity (each
    server observes every level once) stays visible.  Chip-profiler
    captures (FHH_PROFILE) ride along under ``profile`` with the trace
    ids they were taken in.  Present only when some histogram (or
    capture) exists — pre-SLO runs omit the section entirely."""
    merged: dict = {}
    by_reg: dict = {}
    for name, snap in registries.items():
        for hname, hsnap in (snap.get("hists") or {}).items():
            h = Histogram.from_snapshot(hsnap)
            if hname in merged:
                merged[hname].merge(h)
            else:
                merged[hname] = h
            by_reg.setdefault(hname, {})[name] = {
                k: v for k, v in hsnap.items() if k != "buckets"
            }
    captures = tracemod.profile_captures()
    if not merged and not captures:
        return None
    out: dict = {}
    verbs: dict = {}
    for hname in sorted(merged):
        row = merged[hname].summary()
        if hname.startswith("rpc:"):
            verbs[hname.split(":", 1)[1]] = row
            continue
        row["by_registry"] = by_reg.get(hname, {})
        out[hname] = row
    if verbs:
        out["verbs"] = verbs
    if captures:
        out["profile"] = captures
    return out


def _recovery_summary(registries: dict) -> dict | None:
    """Cross-registry recovery rollup: a RECOVERED run must be
    distinguishable from a fault-free one in the report alone.  Sums the
    supervisor counters (``recoveries`` / ``levels_rerun`` /
    ``shards_rerun``) and the servers' replay-dedup accounting
    (``dedup_hits`` over ``verb_requests`` -> hit rate) across every
    registry.  Present whenever any of those counters exists — a
    supervised fault-free run reports zeros, an unsupervised legacy run
    omits the section entirely."""
    names = (
        "recoveries", "levels_rerun", "shards_rerun",
        "dedup_hits", "verb_requests",
    )
    sums = dict.fromkeys(names, 0)
    seen = False
    for snap in registries.values():
        counters = snap.get("counters", {})
        for n in names:
            if n in counters:
                seen = True
                sums[n] += counters[n].get("total", 0)
    if not seen:
        return None
    return {
        "count": sums["recoveries"],
        "levels_rerun": sums["levels_rerun"],
        "shards_rerun": sums["shards_rerun"],
        "dedup_hits": sums["dedup_hits"],
        "dedup_hit_rate": round(
            sums["dedup_hits"] / max(1, sums["verb_requests"]), 6
        ),
    }


def _pipeline_summary(registries: dict) -> dict | None:
    """Cross-registry pipelined-crawl rollup (protocol/leader_rpc.py's
    bounded-depth span pipeline): per level and overall, the configured
    in-flight ``depth``, ``overlap_seconds`` (span busy-time the pipeline
    hid behind the level's wall-clock), and ``stalls`` (head-of-line
    reassembly waits while a later span had already finished), plus
    ``faults`` whenever a mid-flight failure quiesced the pipeline into
    the sequential fallback.  Present only when a pipelined crawl ran —
    sequential (depth 1) runs never emit these metrics."""
    depth_by, overlap_by, stall_by = {}, {}, {}
    overlap_total = stalls_total = faults_total = 0
    depth_last = None
    seen = False
    for snap in registries.values():
        g = snap.get("gauges", {}).get("pipeline_depth")
        if g is not None:
            seen = True
            depth_last = g.get("last")
            depth_by.update(g.get("by_level", {}))
        t = snap.get("phases", {}).get("pipeline_overlap")
        if t is not None:
            seen = True
            overlap_total += t.get("seconds", 0.0)
            for lvl, s in t.get("by_level", {}).items():
                overlap_by[lvl] = overlap_by.get(lvl, 0.0) + s
        for name, total, by in (
            ("pipeline_stalls", "stalls", stall_by),
            ("pipeline_faults", "faults", None),
        ):
            c = snap.get("counters", {}).get(name)
            if c is None:
                continue
            seen = True
            if total == "stalls":
                stalls_total += c.get("total", 0)
                for lvl, n in c.get("by_level", {}).items():
                    by[lvl] = by.get(lvl, 0) + n
            else:
                faults_total += c.get("total", 0)
    if not seen:
        return None
    levels = sorted(
        set(depth_by) | set(overlap_by) | set(stall_by), key=lambda k: int(k)
    )
    return {
        "depth": depth_last,
        "overlap_seconds": round(overlap_total, 6),
        "stalls": stalls_total,
        "faults": faults_total,
        "by_level": {
            lvl: {
                "depth": depth_by.get(lvl),
                "overlap_seconds": round(overlap_by.get(lvl, 0.0), 6),
                "stalls": stall_by.get(lvl, 0),
            }
            for lvl in levels
        },
    }


def _secure_kernel_summary(registries: dict) -> dict | None:
    """Cross-registry secure-kernel rollup (the acceptance instrument of
    the device-resident GC/OT work): per phase, total seconds summed
    across every registry (garbler and evaluator roles alternate per
    level, so one server's registry holds half of each phase), plus the
    per-level union breakdown and the equality-test path actually taken
    (``ot2s`` / ``gc`` / ``mixed`` from the ot_path_* counters).
    Present only when a secure crawl ran — trusted runs never emit these
    metrics."""
    names = ("otext", "garble", "eval", "b2a")
    totals = dict.fromkeys(names, 0.0)
    by_level: dict = {}
    paths = {"ot2s": 0, "gc": 0}
    kshards = None
    kgather = 0.0
    seen = False
    for snap in registries.values():
        phases = snap.get("phases", {})
        for n in names:
            t = phases.get(n)
            if t is None:
                continue
            seen = True
            totals[n] += t.get("seconds", 0.0)
            for lvl, s in t.get("by_level", {}).items():
                by_level.setdefault(lvl, dict.fromkeys(names, 0.0))
                by_level[lvl][n] += s
        for p in paths:
            c = snap.get("counters", {}).get(f"ot_path_{p}")
            if c is not None:
                seen = True
                paths[p] += c.get("total", 0)
        g = snap.get("gauges", {}).get("kernel_shards")
        if g is not None:
            kshards = g.get("last") if kshards is None else max(
                kshards, g.get("last")
            )
        t = phases.get("kernel_gather")
        if t is not None:
            kgather += t.get("seconds", 0.0)
    if not seen:
        return None
    if paths["ot2s"] and paths["gc"]:
        ot_path = "mixed"
    elif paths["gc"]:
        ot_path = "gc"
    else:
        ot_path = "ot2s"
    return {
        "ot_path": ot_path,
        "levels_ot2s": paths["ot2s"],
        "levels_gc": paths["gc"],
        # kernel-stage layout (multi-chip servers only; None/0.0 on a
        # single-device crawl — see the mesh section for the per-level
        # breakdown): the phase seconds above are the SHARDED kernels'
        # whenever kernel_shards > 1
        "kernel_shards": kshards,
        "kernel_gather_seconds": round(kgather, 6),
        **{f"{n}_seconds": round(totals[n], 6) for n in names},
        "by_level": {
            lvl: {n: round(v[n], 6) for n in names}
            for lvl, v in sorted(by_level.items(), key=lambda kv: int(kv[0]))
        },
    }


def _sketch_summary(registries: dict) -> dict | None:
    """Cross-registry malicious-sketch rollup (the device-resident
    sharded verify, parallel/sketch_shard.py): total verify seconds
    (the per-level ``sketch`` phase summed across both servers), the
    levels verified, and the verify's shard layout (``sketch_shards``
    gauge — max across levels; 1 = the single fused program).  Present
    only when a sketch verification ran — semi-honest runs never emit
    these metrics."""
    seconds = 0.0
    levels: set = set()
    shards = None
    seen = False
    for snap in registries.values():
        t = snap.get("phases", {}).get("sketch")
        if t is not None:
            seen = True
            seconds += t.get("seconds", 0.0)
            levels |= set(t.get("by_level", {}))
        g = snap.get("gauges", {}).get("sketch_shards")
        if g is not None:
            seen = True
            vals = [v for v in g.get("by_level", {}).values()]
            if g.get("last") is not None:
                vals.append(g["last"])
            if vals:
                m = max(vals)
                shards = m if shards is None else max(shards, m)
    if not seen:
        return None
    return {
        "verify_seconds": round(seconds, 6),
        "levels_verified": len(levels),
        "sketch_shards": shards,
    }


def _ingest_summary(registries: dict) -> dict | None:
    """Cross-registry streaming-ingest rollup (the windowed front door,
    protocol/leader_rpc.WindowedIngest): admitted/shed keys and rejected
    (Overloaded) attempts, sealed-window count, sustained admission rate
    over the ingest phase's wall-clock, and the windowed crawls' total
    seconds.  The driver's dedicated ``ingest`` registry is the source
    of truth (servers keep their own ``pool_*`` counters for ``status``);
    present only when a streaming run happened — batch-upload runs omit
    the section entirely."""
    names = ("ingest_admitted", "ingest_shed", "ingest_rejected",
             "ingest_windows")
    sums = dict.fromkeys(names, 0)
    ingest_s = crawl_s = 0.0
    seen = False
    for snap in registries.values():
        counters = snap.get("counters", {})
        for n in names:
            if n in counters:
                seen = True
                sums[n] += counters[n].get("total", 0)
        phases = snap.get("phases", {})
        t = phases.get("ingest")
        if t is not None:
            seen = True
            ingest_s += t.get("seconds", 0.0)
        t = phases.get("window_crawl")
        if t is not None:
            seen = True
            crawl_s += t.get("seconds", 0.0)
    if not seen:
        return None
    return {
        "admitted": sums["ingest_admitted"],
        "shed": sums["ingest_shed"],
        "rejected": sums["ingest_rejected"],
        "windows": sums["ingest_windows"],
        "keys_per_sec": round(
            sums["ingest_admitted"] / ingest_s, 2
        ) if ingest_s > 0 else None,
        "window_crawl_seconds": round(crawl_s, 6),
    }


def _fleet_summary(registries: dict) -> dict | None:
    """Cross-registry fleet rollup (protocol/fleet.py): placement
    decisions, live migrations and whole-host failovers (the placer's
    ``fleet`` registry), plus the per-server ``session_exports`` /
    ``session_imports`` verb counters and the driver-side journal
    replays that made each transfer exactly-once.  Present only when a
    fleet operation happened — single-pair runs omit the section."""
    names = ("placement_decisions", "session_migrations",
             "session_failovers", "session_exports", "session_imports",
             "ingest_migrations", "ingest_failovers", "sessions_retired")
    sums = dict.fromkeys(names, 0)
    seen = False
    for snap in registries.values():
        counters = snap.get("counters", {})
        for n in names:
            if n in counters:
                seen = True
                sums[n] += counters[n].get("total", 0)
    if not seen:
        return None
    return {
        "placement_decisions": sums["placement_decisions"],
        "session_migrations": sums["session_migrations"],
        "session_failovers": sums["session_failovers"],
        "session_exports": sums["session_exports"],
        "session_imports": sums["session_imports"],
        "sessions_retired": sums["sessions_retired"],
    }


def _mesh_summary(registries: dict) -> dict | None:
    """Cross-registry multi-chip rollup (per-server client sharding,
    parallel/server_mesh.py): the shard count the crawl ran at
    (``data_shards`` gauge, per level), total + per-level
    ``ici_reduce_seconds`` (the pre-wire psum's cost instrument — fetch-
    synced, so these are real seconds), and the device-loss recovery
    counters (``mesh_reshards`` — frontier re-placed from a host-side
    checkpoint; ``mesh_faults`` — every injected/detected mesh fault).
    Present only when a multi-chip crawl ran — single-device servers
    never emit these metrics."""
    shards_last = None
    shards_by: dict = {}
    kshards_last = None
    kshards_by: dict = {}
    ici_total = kgather_total = 0.0
    ici_by: dict = {}
    reshards = faults = kgathers = 0
    seen = False
    for snap in registries.values():
        g = snap.get("gauges", {}).get("data_shards")
        if g is not None:
            seen = True
            shards_last = g.get("last")
            shards_by.update(g.get("by_level", {}))
        g = snap.get("gauges", {}).get("kernel_shards")
        if g is not None:
            seen = True
            kshards_last = g.get("last")
            for lvl, v in g.get("by_level", {}).items():
                kshards_by[lvl] = max(kshards_by.get(lvl, 0), v)
        t = snap.get("phases", {}).get("ici_reduce")
        if t is not None:
            seen = True
            ici_total += t.get("seconds", 0.0)
            for lvl, s in t.get("by_level", {}).items():
                ici_by[lvl] = ici_by.get(lvl, 0.0) + s
        t = snap.get("phases", {}).get("kernel_gather")
        if t is not None:
            seen = True
            kgather_total += t.get("seconds", 0.0)
        c = snap.get("counters", {}).get("kernel_gathers")
        if c is not None:
            seen = True
            kgathers += c.get("total", 0)
        for name in ("mesh_reshards", "mesh_faults"):
            c = snap.get("counters", {}).get(name)
            if c is None:
                continue
            seen = True
            if name == "mesh_reshards":
                reshards += c.get("total", 0)
            else:
                faults += c.get("total", 0)
    if not seen:
        return None
    levels = sorted(set(shards_by) | set(ici_by), key=lambda k: int(k))
    return {
        "data_shards": shards_last,
        "ici_reduce_seconds": round(ici_total, 6),
        # row-sharded secure kernel stage (parallel/kernel_shard.py):
        # the active kernel-shard count (1 = the degraded gather path).
        # kernel_gathers counts exactly the crawl levels that gathered
        # the packed share bits onto one device — the LAYOUT detector
        # (0 on a fully sharded crawl); kernel_gather_seconds is those
        # gathers' dispatch time (the transfer completes lazily under
        # the level's later fetch), a supplement to the counter
        "kernel_shards": kshards_last,
        "kernel_gathers": kgathers,
        "kernel_gather_seconds": round(kgather_total, 6),
        "reshards": reshards,
        "faults": faults,
        "by_level": {
            lvl: {
                "data_shards": shards_by.get(lvl),
                "ici_reduce_seconds": round(ici_by.get(lvl, 0.0), 6),
                **(
                    {"kernel_shards": kshards_by[lvl]}
                    if lvl in kshards_by
                    else {}
                ),
            }
            for lvl in levels
        },
    }


def _sessions_summary(registries: dict) -> dict | None:
    """Cross-registry multi-tenant rollup (per-collection sessions,
    protocol/sessions.py + tenancy.py): per collection, the crawl phase
    seconds and ingest counters summed across its per-session
    registries (named ``server{N}:{key}`` / ``leader:{key}``; the
    default collection rides the bare ``server{N}``/``leader``
    registries and is NOT broken out), plus the tenant scheduler's
    device-turn/stall-fill accounting (``tenant_device_turns`` /
    ``tenant_stall_fills`` on the server registries — a stall fill is a
    device dispatch that ran while ANOTHER collection waited on the
    GC/OT wire, i.e. the idle gap multi-tenancy exists to fill).
    Present only when a multi-tenant run happened — single-collection
    runs omit the section entirely."""
    per: dict = {}
    turns = fills = 0
    seen = False
    for name, snap in registries.items():
        counters = snap.get("counters", {})
        for cname, total_key in (
            ("tenant_device_turns", "turns"),
            ("tenant_stall_fills", "fills"),
        ):
            c = counters.get(cname)
            if c is None:
                continue
            # turns/fills alone do NOT make the section present: every
            # crawl takes device turns — only a per-session registry
            # (a non-default collection) marks a multi-tenant run
            if total_key == "turns":
                turns += c.get("total", 0)
            else:
                fills += c.get("total", 0)
        base = name.split("#", 1)[0]  # strip the dedup suffix
        if ":" not in base:
            continue
        seen = True
        key = base.split(":", 1)[1]
        row = per.setdefault(
            key,
            {"crawl_seconds": 0.0, "levels": 0, "ingest_admitted": 0,
             "data_bytes": 0},
        )
        # heartbeat-gap instrument: the server stamps a per-session
        # last_progress_ts gauge at every verb completion, so a wedged
        # tenant is visible from the report (and live from ``status``)
        # without reading logs — the age here is "as of report time"
        g = snap.get("gauges", {}).get("last_progress_ts")
        if g is not None and g.get("last") is not None:
            row["last_progress_s"] = round(
                max(0.0, time.time() - float(g["last"])), 3
            )
        phases = snap.get("phases", {})
        for ph in ("fss", "gc_ot", "field"):
            t = phases.get(ph)
            if t is not None:
                row["crawl_seconds"] += t.get("seconds", 0.0)
                lv = [int(k) for k in t.get("by_level", {})]
                if lv:
                    row["levels"] = max(row["levels"], max(lv) + 1)
        for cname in ("pool_admitted_keys", "ingest_admitted"):
            c = counters.get(cname)
            if c is not None:
                row["ingest_admitted"] += c.get("total", 0)
        for cname in ("data_bytes_sent", "data_bytes_recv"):
            c = counters.get(cname)
            if c is not None:
                row["data_bytes"] += c.get("total", 0)
    if not seen:
        return None
    for row in per.values():
        row["crawl_seconds"] = round(row["crawl_seconds"], 6)
    return {
        "count": len(per),
        "device_turns": turns,
        "stall_fills": fills,
        "fill_ratio": round(fills / max(1, turns), 6),
        "per_session": dict(sorted(per.items())),
    }


def write_run_report(path: str, registries=None) -> dict:
    rep = run_report(registries)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(rep, f, indent=1)
    os.replace(tmp, path)  # atomic: a SIGKILL mid-write leaves no torn file
    return rep


def maybe_write_run_report(registries=None) -> str | None:
    """Write to ``$FHH_RUN_REPORT`` if set; returns the path written."""
    path = os.environ.get("FHH_RUN_REPORT")
    if not path:
        return None
    write_run_report(path, registries)
    return path


def per_process_report_path(path: str, tag: str) -> str:
    """``/tmp/r.json`` + ``s0`` -> ``/tmp/r.s0.json``.  Multi-process
    deployments (socket servers, 2-process mesh) inherit ONE
    ``FHH_RUN_REPORT`` path from the shared environment, and each process
    writes the whole document atomically at exit — without a per-process
    suffix the last exiter silently clobbers the other parties' reports."""
    root, ext = os.path.splitext(path)
    return f"{root}.{tag}{ext}"


def claim_report_path(tag: str) -> None:
    """Rewrite this process's ``$FHH_RUN_REPORT`` to its per-process
    path (no-op when the env var is unset)."""
    path = os.environ.get("FHH_RUN_REPORT")
    if path:
        os.environ["FHH_RUN_REPORT"] = per_process_report_path(path, tag)


def _sigterm(_sig, _frame):
    raise SystemExit(143)


@contextlib.contextmanager
def exit_report(heartbeat_default_s: float = 30.0):
    """The binaries' shared exit contract: SIGTERM -> ``SystemExit(143)``
    (so the ``finally`` runs instead of the default immediate kill),
    heartbeat on, and the run report written on the way out — a
    timed-out/killed run still leaves the per-level accounting it
    accumulated plus a heartbeat trail naming the phase it died in."""
    from .heartbeat import start_heartbeat

    signal.signal(signal.SIGTERM, _sigterm)
    start_heartbeat(heartbeat_default_s)
    try:
        yield
    finally:
        maybe_write_run_report()
        tracemod.flush()  # the trace ring survives the exit too
