"""fhh-trace: cross-process distributed tracing for the crawl stack.

One crawl (or one ingest window) involves a leader and two collector
servers exchanging dozens of verbs per level; each process's registries
time their own spans, but nothing ties "server0 spent 300 ms in gc_ot at
level 7" to THE verb the leader issued — which is exactly what
diagnosing a missed clients/sec target needs.  This module adds that
tie, with the same zero-cost-when-disabled contract as
``FHH_DEBUG_GUARDS``:

- **Trace context** — the leader mints a ``trace_id`` per crawl/window
  (:func:`root`); every :meth:`CollectorClient.call` allocates a
  ``span_id`` for the verb and carries ``{"t", "s", "p"}`` in the
  request dict; the server activates that context around the verb's
  execution (:func:`activate`), so every existing ``Registry.span`` in
  the verb's dynamic extent records as a child of the leader's call.
  Replays resend the SAME span id with the same req_id, and the
  server's dedup cache answers them without re-executing — so a span is
  recorded exactly once per execution, never per delivery.
- **Per-process JSONL ring** — events append to
  ``$FHH_TRACE_DIR/fhh_trace_<tag>_<pid>.jsonl``; at
  ``FHH_TRACE_RING`` events (default 200k) the file rotates once to a
  ``.1`` sibling, so a long-lived server is bounded at two segments.
- **Clock correction** — every ``__hello__`` and ``status`` response
  carries the server's wall clock; the client records the NTP-style
  midpoint offset (server_clock - leader_clock) as a ``C`` record.
  :func:`merge` subtracts each component's offset so the merged
  timeline is in LEADER time.
- **Perfetto export** — ``python -m fuzzyheavyhitters_tpu.obs.trace
  merge -d $FHH_TRACE_DIR -o trace.json`` emits one Chrome-trace JSON:
  one "process" track per component (leader / server0 / server1 /
  per-session registries), one thread per collection.
  :func:`validate` is the structural gate tests and CI assert on:
  every parented event's parent exists, durations are non-negative,
  and clock offsets are finite.
- **Chip profiler hooks** — ``FHH_PROFILE=<dir>`` wraps each crawl
  (or only levels named by ``FHH_PROFILE_LEVELS=2,3``) in
  ``jax.profiler`` start/stop, recording the capture alongside the
  active trace id so an XLA timeline is joinable to the Perfetto view.

Events are small dicts, one JSON object per line::

    {"ph": "X", "name": "gc_ot", "comp": "server0", "ts": ..., "dur": ...,
     "trace": "crawl-ab12-1", "span": "ab12-7", "parent": "ab12-3",
     "level": 5, "error": false}

``ph``: "X" complete span, "i" instant, "C" clock offset.  ``ts``/"dur"
are SECONDS (epoch / elapsed); merge converts to Chrome-trace µs.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import math
import os
import sys
import threading
import time

from ..utils import taint_guard

ENV_DIR = "FHH_TRACE_DIR"
ENV_RING = "FHH_TRACE_RING"
ENV_PROFILE = "FHH_PROFILE"
ENV_PROFILE_LEVELS = "FHH_PROFILE_LEVELS"

_DEFAULT_RING = 200_000

# (trace_id, current_span_id) for the running task; None = no trace
_CTX: contextvars.ContextVar = contextvars.ContextVar(
    # fhh-lint: disable=metric-naming (contextvar name, not a series)
    "fhh_trace_ctx", default=None
)

_LOCK = threading.Lock()
# set-once enabled flag: resolved from the env on first use; the
# lock-free read in enabled() is a benign race on an immutable value
# (writers hold _LOCK; _refresh() is the test hook)
_ENABLED: "bool | None" = None
# _WRITER/_TAG/_ENABLED: written only under _LOCK; the lock-free reads
# on the event fast path are benign races on set-once values (a stale
# None just means one more trip through the locked slow path).  NOT
# fhh-guard-bound for exactly that reason — binding them would outlaw
# the deliberate fast-path read.
_WRITER = None
_TAG: "str | None" = None
_CAPTURES: list = []  # fhh-guard: _CAPTURES=_LOCK
_PROF_ACTIVE = [False]  # one profiler session at a time (jax limitation)

# process-unique span-id prefix + counter: ids stay unique across the
# leader and both servers without coordination
_PROC_ID = f"{os.getpid():x}{int(time.time() * 1e3) & 0xFFF:03x}"
_SEQ = itertools.count(1)


def enabled() -> bool:
    global _ENABLED
    e = _ENABLED
    if e is None:
        with _LOCK:
            if _ENABLED is None:
                _ENABLED = bool(os.environ.get(ENV_DIR))
            e = _ENABLED
    return e


def _refresh() -> None:
    """Test hook: re-resolve the env knobs and drop the writer."""
    global _ENABLED, _WRITER, _TAG
    with _LOCK:
        if _WRITER is not None:
            _WRITER.close()
        _ENABLED = None
        _WRITER = None
        _TAG = None
        del _CAPTURES[:]


def claim_tag(tag: str) -> None:
    """Name this process's trace file (``leader`` / ``s0`` / ``s1``);
    called by the binaries before the first event.  Purely cosmetic —
    the pid keeps file names unique either way."""
    global _TAG
    with _LOCK:
        if _WRITER is None:  # too late once the file is open
            _TAG = tag


class _Writer:
    """Append-only JSONL ring: one live segment plus one rotated
    ``.1`` sibling — bounded disk for a long-lived server."""

    def __init__(self, trace_dir: str, tag: str, ring: int):
        os.makedirs(trace_dir, exist_ok=True)
        self.path = os.path.join(trace_dir, f"fhh_trace_{tag}.jsonl")
        self.ring = max(1024, ring)
        self._lock = threading.Lock()
        # line-buffered: a SIGKILLed process loses at most the torn tail
        # line (which load_events skips), not a whole buffer of spans
        self._f = open(self.path, "w", encoding="utf-8", buffering=1)
        self._n = 0

    def write(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":"))
        with self._lock:
            if self._f is None:
                return
            if self._n >= self.ring:
                self._f.close()
                os.replace(self.path, self.path + ".1")
                self._f = open(self.path, "w", encoding="utf-8", buffering=1)
                self._n = 0
            self._f.write(line + "\n")
            self._n += 1

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def _writer() -> "_Writer | None":
    global _WRITER
    if not enabled():
        return None
    w = _WRITER
    if w is None:
        with _LOCK:
            if _WRITER is None:
                trace_dir = os.environ.get(ENV_DIR)
                if not trace_dir:
                    return None
                try:
                    ring = int(os.environ.get(ENV_RING, _DEFAULT_RING))
                except ValueError:
                    ring = _DEFAULT_RING
                tag = _TAG or "p"
                try:
                    _WRITER = _Writer(
                        trace_dir, f"{tag}_{os.getpid()}", ring
                    )
                except OSError as e:
                    # a bad trace dir must degrade, never take down the
                    # crawl telemetry exists to observe
                    from . import logs

                    logs.emit(
                        "trace.disabled", severity="warn",
                        dir=trace_dir, error=str(e),
                    )
                    global _ENABLED
                    _ENABLED = False
                    return None
            w = _WRITER
    return w


def _event(rec: dict) -> None:
    # every span/instant/call record funnels through here: the one
    # place the shadow-taint sanitizer can watch the whole trace plane
    taint_guard.check(rec, sink="trace-event")
    w = _writer()
    if w is not None:
        w.write(rec)


def flush() -> None:
    with _LOCK:
        w = _WRITER
    if w is not None:
        w.flush()


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------


def _new_id() -> str:
    return f"{_PROC_ID}-{next(_SEQ)}"


def current_ids() -> "tuple | None":
    """(trace_id, span_id) of the running task, or None."""
    return _CTX.get()


def current_trace_id() -> "str | None":
    ctx = _CTX.get()
    return None if ctx is None else ctx[0]


@contextlib.contextmanager
def root(kind: str):
    """Mint a trace id for one crawl/window — the leader-side entry
    point.  Reuses an already-active trace (a windowed crawl nested
    inside its window's trace stays ONE trace) and is a no-op when
    tracing is disabled.  Yields the active trace id (or None)."""
    if not enabled():
        yield None
        return
    ctx = _CTX.get()
    if ctx is not None:
        yield ctx[0]  # nested: one trace per outermost root
        return
    tid = f"{kind}-{_new_id()}"
    tok = _CTX.set((tid, None))
    try:
        yield tid
    finally:
        try:
            _CTX.reset(tok)
        except ValueError:
            pass  # exited from a different task/context: drop the reset


def wire_ctx() -> "tuple[dict, list] | None":
    """Allocate the span a CollectorClient.call carries on the wire:
    returns ``({"t", "s", "p"}, state-for-call_event)`` or None when no
    trace is active.  The span id is minted ONCE per call and replayed
    verbatim with the req_id, so replays dedup by (trace_id, span_id)
    exactly like req_ids."""
    ctx = _CTX.get()
    if ctx is None:
        return None
    tid, parent = ctx
    sid = _new_id()
    return {"t": tid, "s": sid, "p": parent}, [tid, sid, parent, time.time()]


def call_event(verb: str, comp: str, state: list, error: bool = False) -> None:
    """Record the client-side verb call as one complete span (the span
    id the wire carried — the server's verb span parents under it)."""
    tid, sid, parent, t0 = state
    rec = {
        "ph": "X",
        "name": f"call:{verb}",
        "comp": comp,
        "ts": round(t0, 6),
        "dur": round(time.time() - t0, 6),
        "trace": tid,
        "span": sid,
    }
    if parent is not None:
        rec["parent"] = parent
    if error:
        rec["error"] = True
    _event(rec)


def activate(tctx) -> "contextvars.Token | None":
    """Server side: enter the trace context a request carried (the verb
    span and everything nested record as children of the wire span)."""
    if not isinstance(tctx, dict):
        return None
    tid, sid = tctx.get("t"), tctx.get("s")
    if not tid:
        return None
    return _CTX.set((tid, sid))


def deactivate(token) -> None:
    if token is None:
        return
    try:
        _CTX.reset(token)
    except ValueError:
        pass  # reset from another task/context: the ctx dies with it


# -- span recording (driven by obs.metrics._SpanCtx) ------------------------


def span_begin() -> "list | None":
    """Open a trace span under the active context; returns opaque state
    for :func:`span_end`, or None when no trace is active.  Callers
    check :func:`enabled` first — this is the slow path."""
    ctx = _CTX.get()
    if ctx is None:
        return None
    tid, parent = ctx
    sid = _new_id()
    tok = _CTX.set((tid, sid))
    return [tid, sid, parent, tok, time.time()]


def span_end(
    state: list, name: str, comp: str,
    level=None, error: bool = False,
) -> None:
    tid, sid, parent, tok, t0 = state
    try:
        _CTX.reset(tok)
    except ValueError:
        pass  # entered/exited across tasks (manually managed span ctx)
    rec = {
        "ph": "X",
        "name": name,
        "comp": comp,
        "ts": round(t0, 6),
        "dur": round(time.time() - t0, 6),
        "trace": tid,
        "span": sid,
    }
    if parent is not None:
        rec["parent"] = parent
    if level is not None:
        rec["level"] = level
    if error:
        rec["error"] = True
    _event(rec)


def instant(name: str, comp: str, trace_id=None, parent=None, **args) -> None:
    """One instant event (chaos faults, plane-frame arrivals,
    heartbeat wedge markers).  ``trace_id``/``parent`` tie it to a span
    when known; otherwise it lands on the component's track only."""
    if not enabled():
        return
    rec = {
        "ph": "i",
        "name": name,
        "comp": comp,
        "ts": round(time.time(), 6),
    }
    if trace_id is not None:
        rec["trace"] = trace_id
    if parent is not None:
        rec["parent"] = parent
    if args:
        rec["args"] = args
    _event(rec)


def wire_tag() -> "tuple | None":
    """(trace_id, span_id) to stamp onto a data-plane frame's session
    header, or None outside any trace."""
    ctx = _CTX.get()
    if ctx is None or ctx[1] is None:
        return None
    return ctx


def note_clock(comp: str, offset_s: float, rtt_s: float) -> None:
    """Record a clock-offset measurement for ``comp`` (NTP-style
    midpoint: server_clock - leader_clock); :func:`merge` applies the
    median per component."""
    if not enabled():
        return
    _event({
        "ph": "C",
        "comp": comp,
        "ts": round(time.time(), 6),
        "off": round(float(offset_s), 6),
        "rtt": round(float(rtt_s), 6),
    })


# ---------------------------------------------------------------------------
# chip profiler hooks
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def profile_capture(kind: str, level=None):
    """Wrap one crawl (``level=None``) or one crawl level in a JAX
    profiler capture when ``FHH_PROFILE=<dir>`` is set.  With
    ``FHH_PROFILE_LEVELS=2,5`` only those levels capture (and the
    whole-crawl hook stands down); without it the whole-crawl hook
    captures and the per-level hooks stand down.  The capture is
    recorded with the ACTIVE trace id, so the XLA timeline joins the
    Perfetto view.  Yields True only while a capture is live."""
    prof_dir = os.environ.get(ENV_PROFILE)
    if not prof_dir:
        yield False
        return
    level_spec = os.environ.get(ENV_PROFILE_LEVELS)
    if level_spec:
        try:
            want = {int(x) for x in level_spec.split(",") if x.strip()}
        except ValueError:
            want = set()
        if level is None or int(level) not in want:
            yield False
            return
    elif level is not None:
        yield False  # whole-crawl mode: the per-level hooks stand down
        return
    with _LOCK:
        if _PROF_ACTIVE[0]:  # one profiler session at a time
            yield False
            return
        _PROF_ACTIVE[0] = True
    started = False
    try:
        try:
            import jax

            os.makedirs(prof_dir, exist_ok=True)
            jax.profiler.start_trace(prof_dir)
            started = True
        except Exception as e:  # fhh-lint: disable=broad-except (profiler availability boundary: a missing/busy profiler degrades the capture, never the crawl)
            from . import logs

            logs.emit(
                "profile.unavailable", severity="warn",
                dir=prof_dir, error=f"{type(e).__name__}: {e}",
            )
        yield started
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # fhh-lint: disable=broad-except (teardown of a best-effort capture)
                pass
            cap = {
                "dir": prof_dir,
                "kind": kind,
                "level": None if level is None else int(level),
                "trace": current_trace_id(),
                "ts": round(time.time(), 3),
            }
            with _LOCK:
                _CAPTURES.append(cap)
            from . import logs

            logs.emit("profile.captured", **cap)
        with _LOCK:
            _PROF_ACTIVE[0] = False


def profile_captures() -> list:
    """Every profiler capture this process recorded (run-report input)."""
    with _LOCK:
        return list(_CAPTURES)


# ---------------------------------------------------------------------------
# merge / validate / CLI
# ---------------------------------------------------------------------------


def load_events(trace_dir: str) -> list:
    """Every event in every ring segment under ``trace_dir`` (rotated
    ``.1`` siblings included), ts-sorted.  Torn tail lines (a process
    killed mid-write) are skipped, not fatal."""
    events = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return events
    for name in names:
        # fhh-lint: disable=metric-naming (ring-file prefix, not a series)
        if not (name.startswith("fhh_trace_") and ".jsonl" in name):
            continue
        try:
            with open(os.path.join(trace_dir, name), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail of a killed process
        except OSError:
            continue
    events.sort(key=lambda e: e.get("ts", 0.0))
    return events


def clock_offsets(events: list) -> dict:
    """component -> best measured offset (seconds): the sample with the
    SMALLEST rtt wins (standard NTP practice — the midpoint error is
    bounded by half the rtt, so the tightest round trip is the most
    trustworthy; a chaos-era sample taken across a reconnect carries a
    huge rtt and loses automatically).  Ties/missing rtt fall back to
    the median.  A component prefix match applies the offset to
    per-session registries too (``server0:tenant`` corrects by
    ``server0``'s)."""
    by_comp: dict = {}
    for e in events:
        if e.get("ph") == "C":
            by_comp.setdefault(e.get("comp", ""), []).append(
                (float(e.get("rtt", math.inf)), float(e.get("off", 0.0)))
            )
    out = {}
    for comp, samples in by_comp.items():
        best_rtt, best_off = min(samples)
        if math.isfinite(best_rtt):
            out[comp] = best_off
        else:  # no rtt recorded anywhere: median of the offsets
            offs = sorted(off for _rtt, off in samples)
            out[comp] = offs[len(offs) // 2]
    return out


def _offset_for(comp: str, offsets: dict) -> float:
    if comp in offsets:
        return offsets[comp]
    base = comp.split(":", 1)[0]
    return offsets.get(base, 0.0)


def to_chrome(events: list) -> dict:
    """Chrome-trace JSON: one pid per component, one tid per
    (component, collection), clock-corrected to leader time."""
    offsets = clock_offsets(events)
    pids: dict = {}
    tids: dict = {}
    out = []

    def pid_of(comp: str) -> int:
        if comp not in pids:
            pids[comp] = len(pids) + 1
            out.append({
                "ph": "M", "name": "process_name", "pid": pids[comp],
                "tid": 0, "args": {"name": comp},
            })
        return pids[comp]

    def tid_of(comp: str, coll: str) -> int:
        key = (comp, coll)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == comp]) + 1
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid_of(comp),
                "tid": tids[key], "args": {"name": coll},
            })
        return tids[key]

    for e in events:
        ph = e.get("ph")
        if ph == "C":
            continue
        comp = e.get("comp", "?")
        coll = comp.split(":", 1)[1] if ":" in comp else "main"
        ts_us = (e.get("ts", 0.0) - _offset_for(comp, offsets)) * 1e6
        args = {
            k: e[k]
            for k in ("trace", "span", "parent", "level", "error")
            if k in e
        }
        args.update(e.get("args") or {})
        rec = {
            "ph": "X" if ph == "X" else "i",
            "name": e.get("name", "?"),
            "pid": pid_of(comp),
            "tid": tid_of(comp, coll),
            "ts": round(ts_us, 1),
            "args": args,
        }
        if ph == "X":
            rec["dur"] = round(max(0.0, e.get("dur", 0.0)) * 1e6, 1)
        else:
            rec["s"] = "t"
        out.append(rec)
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {"clock_offsets": offsets},
    }


def validate(events: list) -> dict:
    """Structural gate over raw events (pre-merge form): every parented
    event's parent span exists within its trace, no negative durations,
    finite clock offsets.  Returns {ok, errors, spans, traces, ...}."""
    errors = []
    spans_by_trace: dict = {}
    comps = set()
    for e in events:
        comps.add(e.get("comp", "?"))
        if e.get("ph") == "X" and e.get("trace"):
            spans_by_trace.setdefault(e["trace"], set()).add(e.get("span"))
    n_spans = 0
    for e in events:
        ph = e.get("ph")
        if ph == "C":
            off = e.get("off")
            if off is None or abs(float(off)) > 86400:
                errors.append(f"implausible clock offset: {e}")
            continue
        tid = e.get("trace")
        if ph == "X":
            n_spans += 1
            if float(e.get("dur", 0.0)) < 0:
                errors.append(f"negative duration: {e}")
        if tid is None:
            continue  # untraced instants (heartbeat/chaos markers)
        parent = e.get("parent")
        if parent is not None and parent not in spans_by_trace.get(tid, ()):
            errors.append(
                f"orphan {ph} event {e.get('name')!r} (comp "
                f"{e.get('comp')!r}): parent {parent!r} not found in "
                f"trace {tid!r}"
            )
    return {
        "ok": not errors,
        "errors": errors[:50],
        "spans": n_spans,
        "traces": sorted(spans_by_trace),
        "components": sorted(comps),
    }


def merge(trace_dir: str, out_path: str) -> dict:
    """Load every ring under ``trace_dir``, validate, and write the
    merged Perfetto/Chrome trace to ``out_path``.  Returns the
    validation verdict (plus event counts)."""
    events = load_events(trace_dir)
    verdict = validate(events)
    doc = to_chrome(events)
    tmp = f"{out_path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    verdict["events"] = len(events)
    verdict["out"] = out_path
    return verdict


def _main(argv) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="fuzzyheavyhitters_tpu.obs.trace",
        description="merge/validate fhh-trace rings",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("merge", "validate"):
        sp = sub.add_parser(name)
        sp.add_argument(
            "-d", "--dir", default=os.environ.get(ENV_DIR),
            help="trace dir (default: $FHH_TRACE_DIR)",
        )
        if name == "merge":
            sp.add_argument("-o", "--out", default=None)
    args = p.parse_args(argv)
    if not args.dir:
        sys.stderr.write("no trace dir (pass -d or set FHH_TRACE_DIR)\n")
        return 2
    if args.cmd == "merge":
        out = args.out or os.path.join(args.dir, "trace.json")
        verdict = merge(args.dir, out)
    else:
        verdict = validate(load_events(args.dir))
    sys.stdout.write(json.dumps(verdict, indent=1) + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(_main(sys.argv[1:]))
