"""Fixed-bucket latency histograms: the SLO instrument of the obs layer.

Counters and gauges cannot express "p95 seal-to-hitters latency" — a
last-write gauge hides the tail and a mean hides everything.  This
module adds the missing shape: a :class:`Histogram` with FIXED,
log-spaced bucket bounds shared by every histogram in every process.
Fixed bounds are the load-bearing choice: two histograms are merged by
summing their bucket counts, with no re-binning and no per-histogram
metadata to reconcile — which is what lets the run report fold the
leader's, both servers', and every per-session registry's observations
of the same metric into one quantile estimate
(:func:`obs.report.run_report`'s ``slo`` section), and lets ``status``
report a live summary without shipping raw samples.

Layout: 5 buckets per decade from 100 µs to 10 000 s (40 log-spaced
bounds, ~58 % wide — quantile estimates are good to about one bucket
width, plenty for SLO work) plus an underflow-free first bucket and an
overflow bucket.  Values are SECONDS; an exact ``max`` rides along so a
single catastrophic outlier is never rounded into a bucket bound.

Quantiles interpolate within the winning bucket's log-space width, so
p50/p95/p99 move smoothly as counts shift instead of jumping from bound
to bound.
"""

from __future__ import annotations

import bisect
import math

# Upper bounds of the finite buckets: 1e-4 * 10^(i/5) for i in 0..40
# (100 µs .. 10 000 s).  Module-level constant — every histogram in
# every process shares it, which is the whole mergeability contract.
BUCKET_BOUNDS: tuple = tuple(
    round(1e-4 * 10 ** (i / 5), 10) for i in range(41)
)
N_BUCKETS = len(BUCKET_BOUNDS) + 1  # + overflow

_QUANTILES = (0.5, 0.95, 0.99)


class Histogram:
    """One latency histogram over the shared :data:`BUCKET_BOUNDS`.
    Exact ``min``/``max`` ride along so quantile estimates clamp to the
    observed range — a single-sample histogram reports its sample, not
    a bucket midpoint."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = [0] * N_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        v = float(seconds)
        if not math.isfinite(v) or v < 0.0:
            v = 0.0
        self.counts[bisect.bisect_left(BUCKET_BOUNDS, v)] += 1
        self.count += 1
        self.sum += v
        if v > self.max:
            self.max = v
        if v < self.min:
            self.min = v

    # -- merge ------------------------------------------------------------

    def merge(self, other: "Histogram") -> "Histogram":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.max > self.max:
            self.max = other.max
        if other.min < self.min:
            self.min = other.min
        return self

    @classmethod
    def merged(cls, hists) -> "Histogram":
        out = cls()
        for h in hists:
            if h is not None:
                out.merge(h)
        return out

    # -- quantiles --------------------------------------------------------

    def quantile(self, q: float) -> float | None:
        """Estimated value at quantile ``q`` (0..1); None when empty.
        Interpolates log-linearly inside the winning bucket."""
        if self.count == 0:
            return None
        lo_clamp = self.min if math.isfinite(self.min) else 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            seen += c
            if seen < rank:
                continue
            hi = BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) else self.max
            if i == 0:
                lo = 0.0
                # first bucket: linear interpolation (log of 0 is not a number)
                frac = max(0.0, min(1.0, 1 - (seen - rank) / c))
                est = lo + frac * (hi - lo)
            else:
                lo = BUCKET_BOUNDS[i - 1]
                if hi <= lo:  # overflow bucket whose max sits on the bound
                    est = hi if hi > 0 else lo
                else:
                    frac = max(0.0, min(1.0, 1 - (seen - rank) / c))
                    est = math.exp(
                        math.log(lo) + frac * (math.log(hi) - math.log(lo))
                    )
            # clamp to the observed range: small-count quantiles stay
            # honest (one sample reports itself, not a bucket midpoint)
            return min(max(est, lo_clamp), self.max)
        return self.max  # unreachable with count > 0; defensive

    # -- snapshots --------------------------------------------------------

    def summary(self) -> dict:
        """Quantile summary without buckets (the ``status`` form)."""
        out = {
            "count": self.count,
            "sum_s": round(self.sum, 6),
            "min_s": round(self.min, 6) if math.isfinite(self.min) else None,
            "max_s": round(self.max, 6),
        }
        for q in _QUANTILES:
            v = self.quantile(q)
            out[f"p{int(q * 100)}_s"] = None if v is None else round(v, 6)
        return out

    def snapshot(self) -> dict:
        """Summary + sparse buckets (the mergeable run-report form)."""
        out = self.summary()
        out["buckets"] = {
            str(i): c for i, c in enumerate(self.counts) if c
        }
        return out

    @classmethod
    def from_snapshot(cls, snap: dict) -> "Histogram":
        """Rebuild a mergeable histogram from a :meth:`snapshot` dict
        (tolerates summaries without buckets by reconstructing nothing)."""
        h = cls()
        for k, c in (snap.get("buckets") or {}).items():
            i = int(k)
            if 0 <= i < N_BUCKETS:
                h.counts[i] = int(c)
        h.count = int(snap.get("count", sum(h.counts)))
        h.sum = float(snap.get("sum_s", 0.0))
        h.max = float(snap.get("max_s", 0.0))
        mn = snap.get("min_s")
        h.min = math.inf if mn is None else float(mn)
        return h
