"""Metrics registry: counters, gauges, phase timers, and timing spans.

A :class:`Registry` is a named bag of metrics owned by one component —
each collector server owns one (``server0`` / ``server1``), the
in-process driver, the RPC leader, and the mesh leader own theirs, and
everything else (binaries, bench) shares :func:`default_registry`.
Per-component ownership is load-bearing: the bench and the test suite
run both servers in ONE process, and their phase seconds and data-plane
byte counts must stay separable (the run report asserts them consistent
*between* the two servers, which a process-global bag cannot express).

Every metric takes an optional ``level`` label and keeps both a total
and a per-level breakdown — the per-level phase taxonomy the reference
reports as its headline server cost (collect.rs:412-503) is
``timer_add("fss"/"gc_ot"/"field", dt, level=...)`` here.

Spans (:meth:`Registry.span`) are timing contexts that feed the timers
AND mark the registry's "currently running" stack, which the heartbeat
thread reads to name the active phase and level of a wedged run.  A
counter incremented inside a span inherits the span's ``level`` when the
call site doesn't know it (the data-plane byte accounting in
``protocol/rpc.py`` attributes bytes to the level whose exchange sent
them this way).

Thread-safety: one lock per registry guards every mutation and the
report snapshot; the heartbeat thread reads span stacks concurrently
with the owning event loop.  Registration is WEAK with bounded
final-snapshot retention: live registries are discoverable via
:func:`all_registries`, and when an owner (a leader that finished its
crawl, a drained server) is dropped, the registry's final snapshot is
retained (bounded — oldest beyond :data:`_MAX_FINAL` are discarded and
counted) so the end-of-run report still carries its accounting without
a long-lived process that constructs one leader per collection growing
the registry set, the heartbeat sweep, and every report without bound.
"""

from __future__ import annotations

import threading
import time
import weakref

from . import trace as _trace
from .hist import Histogram


class Span:
    """One active timing context (a stack frame of Registry.span).
    After the context exits, ``seconds`` holds the pass's duration —
    callers that need THIS pass's time (not the registry's accumulated
    total, which a re-crawled level would inflate) read it there."""

    __slots__ = ("name", "level", "t0", "seconds")

    def __init__(self, name: str, level: int | None):
        self.name = name
        # numpy level indices coerced here so every keyed breakdown
        # downstream (span inheritance included) uses plain ints
        self.level = None if level is None else _num(level)
        self.t0 = time.perf_counter()
        self.seconds: float | None = None

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0


_REGISTRIES: "weakref.WeakSet[Registry]" = weakref.WeakSet()
# RLock: _retain_final runs from weakref/GC callbacks, which can fire
# synchronously inside an allocation made WHILE this lock is held (e.g.
# list(_REGISTRIES) in all_registries) — a plain Lock would deadlock that
# thread against itself
_GLOBAL_LOCK = threading.RLock()
_DEFAULT: "Registry | None" = None  # fhh-guard: _DEFAULT=_GLOBAL_LOCK
_NEXT_SEQ = 0  # fhh-guard: _NEXT_SEQ=_GLOBAL_LOCK
# final snapshots of dropped registries, as (name, seq, report) — bounded
_MAX_FINAL = 128
_FINAL: "list[tuple[str, int, dict]]" = []  # fhh-guard: _FINAL=_GLOBAL_LOCK
_FINAL_DROPPED = 0  # fhh-guard: _FINAL_DROPPED=_GLOBAL_LOCK


def _retain_final(name: str, seq: int, counters, gauges, timers, hists) -> None:
    """weakref.finalize callback: the owner dropped its registry — keep
    the final snapshot so the end-of-run report still carries this
    component's accounting.  Receives the metric dicts (NOT the registry,
    which the finalizer must not pin); nothing mutates them once the
    owner is gone."""
    global _FINAL_DROPPED
    snap = Registry._snapshot(counters, gauges, timers, hists)
    with _GLOBAL_LOCK:
        _FINAL.append((name, seq, snap))
        if len(_FINAL) > _MAX_FINAL:
            del _FINAL[0]
            _FINAL_DROPPED += 1


def final_snapshots() -> "list[tuple[str, int, dict]]":
    with _GLOBAL_LOCK:
        return list(_FINAL)


def final_dropped() -> int:
    """How many dropped-registry snapshots fell off the retention bound
    (surfaced in the run report so the cap is never silent)."""
    with _GLOBAL_LOCK:
        return _FINAL_DROPPED


def _num(v):
    """Coerce numpy scalars to plain Python numbers at the metric
    boundary, so ``report()`` is always json.dump-able (counter values
    come straight from shape math and ``compact_survivors`` outputs)."""
    return v.item() if hasattr(v, "item") else v


class Registry:
    def __init__(self, name: str = "main"):
        global _NEXT_SEQ
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, dict] = {}
        self._gauges: dict[str, dict] = {}
        self._timers: dict[str, dict] = {}
        self._hists: dict[str, Histogram] = {}
        self._spans: list[Span] = []
        with _GLOBAL_LOCK:
            # registration order breaks name ties deterministically (a
            # process can own two same-named registries, e.g. a second
            # driver.Leader after a checkpoint restore)
            self.seq = _NEXT_SEQ
            _NEXT_SEQ += 1
            _REGISTRIES.add(self)
        weakref.finalize(
            self, _retain_final, self.name, self.seq,
            self._counters, self._gauges, self._timers, self._hists,
        )

    # -- counters / gauges / timers --------------------------------------

    def count(self, name: str, n: float = 1, level: int | None = None) -> None:
        """Add ``n`` to counter ``name``.  ``level=None`` inherits the
        innermost active span's level (if any) — so byte/fetch accounting
        deep in the wire helpers lands on the level whose exchange it
        served without threading the level through every call."""
        n = _num(n)
        with self._lock:
            if level is None:
                level = self._span_level_locked()
            else:
                level = _num(level)
            ent = self._counters.setdefault(name, {"total": 0, "levels": {}})
            ent["total"] += n
            if level is not None:
                ent["levels"][level] = ent["levels"].get(level, 0) + n

    def gauge(self, name: str, value: float, level: int | None = None) -> None:
        """Set gauge ``name`` (last-write-wins, per level and overall)."""
        value = _num(value)
        with self._lock:
            if level is None:
                level = self._span_level_locked()
            else:
                level = _num(level)
            ent = self._gauges.setdefault(name, {"last": value, "levels": {}})
            ent["last"] = value
            if level is not None:
                ent["levels"][level] = value

    def observe(self, name: str, seconds: float) -> None:
        """Record one latency sample into histogram ``name`` (fixed
        log-spaced buckets, obs.hist.Histogram — mergeable across
        registries and processes).  The SLO shape counters/gauges
        cannot express: p50/p95/p99 of per-level crawl latency,
        per-verb RPC latency, seal-to-hitters."""
        seconds = _num(seconds)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(seconds)

    def hist(self, name: str) -> Histogram | None:
        """A merged COPY of histogram ``name`` (callers may merge it
        onward without racing live observes)."""
        with self._lock:
            h = self._hists.get(name)
            return None if h is None else Histogram.merged([h])

    def hists_summary(self) -> dict:
        """{name: quantile summary} for every histogram — the ``status``
        verb's live SLO section (no buckets: bounded response size)."""
        with self._lock:
            return {k: h.summary() for k, h in sorted(self._hists.items())}

    def timer_add(self, name: str, seconds: float, level: int | None = None) -> None:
        seconds = _num(seconds)
        with self._lock:
            ent = self._timers.setdefault(
                name, {"seconds": 0.0, "count": 0, "levels": {}}
            )
            ent["seconds"] += seconds
            ent["count"] += 1
            if level is not None:
                level = _num(level)
                ent["levels"][level] = ent["levels"].get(level, 0.0) + seconds

    # -- spans ------------------------------------------------------------

    def span(self, name: str, level: int | None = None):
        """Timing context: on exit, adds the elapsed seconds to timer
        ``name`` (under ``level``); while active, tops this registry's
        span stack for the heartbeat and for label inheritance."""
        return _SpanCtx(self, name, level)

    def current_span(self) -> Span | None:
        with self._lock:
            return self._spans[-1] if self._spans else None

    def _span_level_locked(self) -> int | None:
        for sp in reversed(self._spans):
            if sp.level is not None:
                return sp.level
        return None

    # -- lifecycle / snapshot ---------------------------------------------

    def reset(self) -> None:
        """Clear accumulated metrics (active spans survive — a reset verb
        can arrive while an outer span is open)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._hists.clear()

    def counter_value(self, name: str, level: int | None = None) -> float:
        with self._lock:
            ent = self._counters.get(name)
            if ent is None:
                return 0
            return ent["total"] if level is None else ent["levels"].get(level, 0)

    def gauge_value(self, name: str, level: int | None = None):
        """Last-written gauge value (None when never set) — the status
        verb's read side of per-level layout gauges like
        ``kernel_shards``."""
        with self._lock:
            ent = self._gauges.get(name)
            if ent is None:
                return None
            return ent["last"] if level is None else ent["levels"].get(level)

    def gauge_max(self, name: str):
        """Maximum over every per-level write of gauge ``name`` (None
        when never set) — how the status verb reports the DEEPEST
        layout a crawl engaged (the last-written value alone hides a
        mid-crawl peak, e.g. a leaf level that degraded to fewer kernel
        shards than the widest inner level)."""
        with self._lock:
            ent = self._gauges.get(name)
            if ent is None:
                return None
            vals = list(ent["levels"].values()) + [ent["last"]]
            return max(vals)

    def timer_seconds(self, name: str, level: int | None = None) -> float:
        with self._lock:
            ent = self._timers.get(name)
            if ent is None:
                return 0.0
            return ent["seconds"] if level is None else ent["levels"].get(level, 0.0)

    def report(self) -> dict:
        """JSON-serializable snapshot.  Level keys become strings (JSON
        objects can't carry int keys); totals stay numbers."""
        with self._lock:
            return self._snapshot(
                self._counters, self._gauges, self._timers, self._hists
            )

    @staticmethod
    def _snapshot(counters, gauges, timers, hists=None) -> dict:
        str_levels = lambda d: {str(k): v for k, v in sorted(d.items())}
        out = {
            "counters": {
                k: {"total": v["total"], "by_level": str_levels(v["levels"])}
                for k, v in sorted(counters.items())
            },
            "gauges": {
                k: {"last": v["last"], "by_level": str_levels(v["levels"])}
                for k, v in sorted(gauges.items())
            },
            "phases": {
                k: {
                    "seconds": v["seconds"],
                    "count": v["count"],
                    "by_level": str_levels(v["levels"]),
                }
                for k, v in sorted(timers.items())
            },
        }
        if hists:
            # key present only when histograms exist: pre-SLO consumers
            # (and the reset-to-empty contract) see the exact old shape
            out["hists"] = {
                k: h.snapshot() for k, h in sorted(hists.items())
            }
        return out


class _SpanCtx:
    __slots__ = ("_reg", "_name", "_level", "_span", "_trace")

    def __init__(self, reg: Registry, name: str, level: int | None):
        self._reg, self._name, self._level = reg, name, level

    def __enter__(self) -> Span:
        self._span = Span(self._name, self._level)
        # distributed tracing (obs.trace): under an active trace context
        # this span records as a child event in the per-process ring —
        # one enabled() flag read when tracing is off (the pinned
        # zero-overhead contract, like FHH_DEBUG_GUARDS)
        self._trace = _trace.span_begin() if _trace.enabled() else None
        with self._reg._lock:
            self._reg._spans.append(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        dt = self._span.seconds = self._span.elapsed()
        with self._reg._lock:
            # remove THIS span (not blindly the top): an exception may
            # unwind contexts out of order across await points
            try:
                self._reg._spans.remove(self._span)
            except ValueError:
                pass
        if self._trace is not None:
            # a span unwound by an exception (a severed data plane
            # failing a mid-exchange verb) records error=true instead of
            # dangling open in the merged trace
            _trace.span_end(
                self._trace, self._name, self._reg.name,
                level=self._span.level, error=exc_type is not None,
            )
        self._reg.timer_add(self._name, dt, self._level)


def default_registry() -> Registry:
    """The process-wide registry for components without their own."""
    global _DEFAULT
    with _GLOBAL_LOCK:
        if _DEFAULT is not None:
            return _DEFAULT
    reg = Registry("main")  # registers itself; construct outside the
    # global lock (Registry.__init__ takes that same lock)
    with _GLOBAL_LOCK:
        if _DEFAULT is None:  # lost the construction race: first one wins
            _DEFAULT = reg
        return _DEFAULT


def all_registries() -> list[Registry]:
    """Every registry created in this process, sorted by name then by
    registration order (so same-named registries keep a stable order)."""
    with _GLOBAL_LOCK:
        regs = list(_REGISTRIES)
    return sorted(regs, key=lambda r: (r.name, r.seq))
