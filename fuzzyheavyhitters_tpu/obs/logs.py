"""Structured log emission: human-readable lines or JSON-lines.

One function, :func:`emit`, replaces every crawl-path ``print``:

    emit("crawl.done", seconds=3.21)
    emit("level.phases", severity="debug", level=5, fss=0.12, ...)

Human mode (default) renders one aligned line per event::

    [fhh 12:33:02 info] crawl.done seconds=3.21

JSON-lines mode (``FHH_LOG_FORMAT=json`` or ``configure(fmt="json")``)
renders the same event as one JSON object per line with an epoch ``ts``
— machine-parseable without scraping free-text (numpy scalars are
coerced to plain Python numbers so the lines round-trip through
``json.loads``).

The stream defaults to stderr so stdout stays a clean program-output
channel (bench.py's contract is "the last stdout line is the JSON
result"); ``FHH_LOG_STREAM`` accepts ``stdout`` / ``stderr`` / a file
path.  Severity gating (``FHH_LOG_LEVEL``, default ``info``) is what
lets the per-level phase breakdown ride at ``debug`` without spamming a
512-level crawl's console.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from ..utils import taint_guard

_SEVERITIES = {"debug": 10, "info": 20, "warn": 30, "error": 40}

# RLock: emit() holds it across _resolve_stream, which takes it again
# around the _opened mutations so it is ALSO safe called standalone
_lock = threading.RLock()
_cfg = {
    "fmt": os.environ.get("FHH_LOG_FORMAT", "human"),
    "stream": os.environ.get("FHH_LOG_STREAM", "stderr"),
    "min_severity": _SEVERITIES.get(
        os.environ.get("FHH_LOG_LEVEL", "info"), 20
    ),
}
_opened: dict = {"path": None, "file": None}  # fhh-guard: _opened=_lock


def configure(fmt: str | None = None, stream=None, min_severity: str | None = None):
    """Override the env-derived config (tests pass a StringIO ``stream``)."""
    with _lock:
        if fmt is not None:
            if fmt not in ("human", "json"):
                raise ValueError(f"unknown log format {fmt!r}")
            _cfg["fmt"] = fmt
        if stream is not None:
            _cfg["stream"] = stream
        if min_severity is not None:
            _cfg["min_severity"] = _SEVERITIES[min_severity]


def _resolve_stream():
    s = _cfg["stream"]
    if s == "stderr":
        return sys.stderr
    if s == "stdout":
        return sys.stdout
    if isinstance(s, str):  # file path: open once, append, keep open
        with _lock:  # reentrant from emit(); guards _opened standalone too
            if _opened["path"] != s:
                if _opened["file"] is not None:
                    try:
                        _opened["file"].close()
                    except OSError:
                        pass
                # record the attempt BEFORE opening: a bad path must degrade
                # to stderr once, not re-raise out of every emit — a telemetry
                # knob misconfiguration may never take down the crawl
                _opened["path"] = s
                try:
                    _opened["file"] = open(s, "a", buffering=1)
                except OSError as e:
                    _opened["file"] = None
                    sys.stderr.write(
                        f"[fhh] cannot open log stream {s!r} ({e}); "
                        "falling back to stderr\n"
                    )
            return _opened["file"] if _opened["file"] is not None else sys.stderr
    return s  # a file-like object (tests)


def _plain(v):
    """Coerce numpy scalars/0-d arrays so JSON lines round-trip."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
        return v.item()
    try:
        return v.item()  # numpy scalar types
    except (AttributeError, ValueError):
        return str(v)


def emit(event: str, severity: str = "info", **fields) -> None:
    sev = _SEVERITIES.get(severity, 20)
    with _lock:
        if sev < _cfg["min_severity"]:
            return
        stream = _resolve_stream()
        if _cfg["fmt"] == "json":
            rec = {"ts": round(time.time(), 3), "sev": severity, "event": event}
            rec.update({k: _plain(v) for k, v in fields.items()})
            # correlate log lines with the distributed trace: when
            # fhh-trace is on and this task runs under a trace context,
            # the line carries the trace id (grep the JSONL for it to
            # jump from a log event to the Perfetto timeline)
            if "trace" not in rec:
                from . import trace as _trace  # lazy: avoid import cycle

                if _trace.enabled():
                    tid = _trace.current_trace_id()
                    if tid is not None:
                        rec["trace"] = tid
            line = json.dumps(rec)
        else:
            kv = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={_plain(v)}"
                for k, v in fields.items()
            )
            ts = time.strftime("%H:%M:%S")
            line = f"[fhh {ts} {severity}] {event}" + (f" {kv}" if kv else "")
        # the fully-rendered line (either format) is the sink surface:
        # the shadow-taint sanitizer byte-checks it once, here
        taint_guard.check(line, sink="log-emit")
        try:
            stream.write(line + "\n")
            stream.flush()
        except (ValueError, OSError):
            pass  # stream closed (interpreter teardown / redirected tests)
