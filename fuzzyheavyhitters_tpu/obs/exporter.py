"""Live /metrics exporter: Prometheus text format over stdlib HTTP.

The forensic layer (run report, trace rings) answers "what happened";
this module answers "what is happening" — every live
:class:`obs.metrics.Registry` counter, gauge, phase timer, and
fixed-bucket histogram, served as Prometheus exposition text from a
daemon thread, so the chip campaign can watch a crawl in flight instead
of reading its postmortem.

One-flag discipline (the trace/``FHH_DEBUG_GUARDS`` contract): the
exporter exists only when ``FHH_METRICS_PORT`` is set.  Unset, a run
pays exactly one ``getenv`` at startup — no socket, no thread, no
per-metric cost (the registries are scraped, never instrumented).

Port layout: each process claims ``base + offset`` by its telemetry tag
(``leader`` -> +0, ``s0`` -> +1, ``s1`` -> +2, anything else -> +0), the
same tag family as the run-report path claim and the trace ring.  A base
of ``0`` binds an ephemeral port (tests; read it back via :func:`port`).
A bind failure DEGRADES with a structured warn — a telemetry knob
misconfiguration may never take down a collector (the PR 1 report-path
discipline).

Naming contract (enforced statically by the fhh-lint ``metric-naming``
rule for literal names): every exported series is
``fhh_<name>[_seconds][_total]`` with ``registry`` (and, for per-session
registries named ``server0:tenant``, ``collection``) labels.  A colon in
a metric name (``fresh_compiles:level``) is an internal sub-name and
becomes a ``key`` label, because ``:`` is reserved in Prometheus
exposition names.

Beyond the registries, a process can register *producers* — callables
returning extra exposition lines (the collector servers publish live
session rows this way, and the alert engine is evaluated per scrape).  A
producer returning ``None`` is pruned (weakref-backed producers outlive
their owner as a tiny dead closure otherwise).
"""

from __future__ import annotations

import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import logs
from ..utils import taint_guard
from .hist import BUCKET_BOUNDS
from .metrics import all_registries

ENV_PORT = "FHH_METRICS_PORT"
ENV_HOST = "FHH_METRICS_HOST"  # default loopback: telemetry, not a service

# tag -> port offset from the FHH_METRICS_PORT base (one process family
# per machine; ops.top scrapes base, base+1, base+2)
PORT_OFFSETS = {"leader": 0, "s0": 1, "s1": 2}

_lock = threading.Lock()
# fhh-guard: _state=_lock
_state: dict = {"server": None, "thread": None, "port": None, "tag": None}
_producers: list = []  # fhh-guard: _producers=_lock

_SANE_RE = re.compile(r"[^a-z0-9_]")


def _sane(name: str) -> str:
    """Coerce an internal metric name into a Prometheus identifier
    chunk: lowercase, every illegal char to ``_``, never digit-led."""
    out = _SANE_RE.sub("_", str(name).lower())
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _esc(value) -> str:
    """Escape a label value per the exposition format."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _labels(registry_name: str, extra: dict | None = None) -> str:
    """Render the label block for one registry.  Per-session registries
    are named ``server0:tenant`` (protocol/sessions.SessionTable); the
    colon splits into ``registry`` + ``collection`` so one family holds
    every tenant's series side by side."""
    reg, _, coll = registry_name.partition(":")
    parts = [f'registry="{_esc(reg)}"']
    if coll:
        parts.append(f'collection="{_esc(coll)}"')
    for k, v in (extra or {}).items():
        parts.append(f'{k}="{_esc(v)}"')
    return "{" + ",".join(parts) + "}"


def _split_key(name: str) -> tuple[str, dict]:
    """``fresh_compiles:level`` -> (``fresh_compiles``, {key: level})."""
    base, _, sub = name.partition(":")
    return _sane(base), ({"key": sub} if sub else {})


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float)):
        return repr(float(v)) if isinstance(v, float) else str(v)
    return "NaN"  # non-numeric gauge (defensive: exporter never raises)


class _Families:
    """Accumulates series grouped by family so each family emits one
    HELP/TYPE header no matter how many registries contribute."""

    def __init__(self):
        self._fam: dict[str, tuple[str, list[str]]] = {}

    def add(self, family: str, typ: str, line: str) -> None:
        ent = self._fam.get(family)
        if ent is None:
            ent = self._fam[family] = (typ, [])
        ent[1].append(line)

    def render(self) -> list[str]:
        out = []
        for family in sorted(self._fam):
            typ, lines = self._fam[family]
            out.append(f"# TYPE {family} {typ}")
            out.extend(lines)
        return out


def _hist_lines(fam: _Families, family: str, labels_base: str, snap: dict) -> None:
    """One histogram snapshot (obs.hist sparse-bucket form) as a
    Prometheus histogram: cumulative ``_bucket`` over the shared
    BUCKET_BOUNDS plus ``+Inf``, then ``_sum`` / ``_count``."""
    counts = [0] * (len(BUCKET_BOUNDS) + 1)
    for k, c in (snap.get("buckets") or {}).items():
        i = int(k)
        if 0 <= i < len(counts):
            counts[i] = int(c)
    strip = labels_base[1:-1]  # inner "k=v,k=v" of the rendered block
    cum = 0
    for i, bound in enumerate(BUCKET_BOUNDS):
        cum += counts[i]
        le = format(bound, ".10g")
        fam.add(
            family, "histogram",
            f'{family}_bucket{{{strip},le="{le}"}} {cum}',
        )
    total = int(snap.get("count", cum + counts[-1]))
    fam.add(
        family, "histogram",
        f'{family}_bucket{{{strip},le="+Inf"}} {total}',
    )
    fam.add(family, "histogram", f"{family}_sum{labels_base} {_fmt(float(snap.get('sum_s', 0.0)))}")
    fam.add(family, "histogram", f"{family}_count{labels_base} {total}")


def render() -> str:
    """The full exposition document: every live registry's snapshot plus
    every producer's extra lines.  Pure read — safe from the HTTP thread
    (``Registry.report`` snapshots under the registry lock)."""
    fam = _Families()
    for reg in all_registries():
        rep = reg.report()
        for name, ent in rep["counters"].items():
            base, extra = _split_key(name)
            family = f"fhh_{base}_total"
            fam.add(family, "counter",
                    f"{family}{_labels(reg.name, extra)} {_fmt(ent['total'])}")
        for name, ent in rep["gauges"].items():
            base, extra = _split_key(name)
            family = f"fhh_{base}"
            fam.add(family, "gauge",
                    f"{family}{_labels(reg.name, extra)} {_fmt(ent['last'])}")
        for name, ent in rep["phases"].items():
            base, extra = _split_key(name)
            lbl = _labels(reg.name, extra)
            fams = f"fhh_{base}_seconds_total"
            famc = f"fhh_{base}_runs_total"
            fam.add(fams, "counter", f"{fams}{lbl} {_fmt(ent['seconds'])}")
            fam.add(famc, "counter", f"{famc}{lbl} {_fmt(ent['count'])}")
        for name, snap in rep.get("hists", {}).items():
            base, extra = _split_key(name)
            family = f"fhh_{base}_seconds"
            _hist_lines(fam, family, _labels(reg.name, extra), snap)
    lines = fam.render()
    # the scrape IS the registry-rule evaluation tick for the alert
    # engine (no thread, no timer): slo burn / post-warmup recompiles /
    # HBM high water are checked against exactly what was just rendered
    from . import alerts  # late: alerts renders via this module too

    alerts.evaluate_registries()
    lines.extend(alerts.metrics_lines())
    with _lock:
        producers = list(_producers)
    dead = []
    for prod in producers:
        try:
            extra_lines = prod()
        # fhh-lint: disable=broad-except (scrape path: a racy producer
        # snapshot may never 500 the exporter or kill its thread)
        except Exception:
            continue
        if extra_lines is None:
            dead.append(prod)
            continue
        lines.extend(extra_lines)
    if dead:
        with _lock:
            for prod in dead:
                if prod in _producers:
                    _producers.remove(prod)
    text = "\n".join(lines) + "\n"
    # the full exposition document is what an outward-facing scrape
    # sees: the shadow-taint sanitizer's most important boundary
    taint_guard.check(text, sink="metrics-render")
    return text


def add_producer(fn) -> None:
    """Register a callable returning extra exposition lines (or ``None``
    once its owner is gone, which prunes it)."""
    with _lock:
        _producers.append(fn)


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path.split("?", 1)[0] != "/metrics":
            self.send_response(404)
            self.end_headers()
            return
        body = render().encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *_args):  # scrapes are not log events
        pass


def maybe_start(tag: str):
    """Start the exporter iff ``FHH_METRICS_PORT`` is set; returns the
    bound port or ``None``.  Idempotent per process; bind/parse failures
    degrade with a structured warn and return ``None`` (a telemetry knob
    may never crash a collector)."""
    raw = os.environ.get(ENV_PORT)
    if not raw:
        return None  # the entire disabled-path cost: one getenv
    with _lock:
        if _state["server"] is not None:
            return _state["port"]
    try:
        base = int(raw)
    except ValueError:
        logs.emit("metrics.disabled", severity="warn", tag=tag,
                  reason=f"bad {ENV_PORT}={raw!r}")
        return None
    port = 0 if base == 0 else base + PORT_OFFSETS.get(tag, 0)
    host = os.environ.get(ENV_HOST, "127.0.0.1")
    try:
        srv = ThreadingHTTPServer((host, port), _Handler)
    except OSError as e:
        logs.emit("metrics.disabled", severity="warn", tag=tag,
                  port=port, reason=repr(e))
        return None
    srv.daemon_threads = True
    th = threading.Thread(
        target=srv.serve_forever, name=f"fhh-metrics-{tag}", daemon=True
    )
    bound = srv.server_address[1]
    with _lock:
        if _state["server"] is not None:  # lost a start race: first wins
            bound = _state["port"]
            srv.server_close()
            return bound
        _state.update(server=srv, thread=th, port=bound, tag=tag)
    th.start()
    logs.emit("metrics.listening", tag=tag, port=bound, host=host)
    return bound


def running() -> bool:
    with _lock:
        return _state["server"] is not None


def port() -> int | None:
    with _lock:
        return _state["port"]


def stop() -> None:
    """Tear the exporter down (tests; binaries just exit)."""
    with _lock:
        srv, th = _state["server"], _state["thread"]
        _state.update(server=None, thread=None, port=None, tag=None)
        _producers.clear()
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if th is not None:
        th.join(timeout=5)
