"""Structured telemetry for the crawl stack — dependency-free.

Four small pieces, one coherent layer (replacing the ad-hoc ``print``
taxonomy that left BENCH_r05's rc=124 postmortem with nothing but an XLA
platform warning):

- :mod:`.metrics` — named counters, gauges, and phase timers with
  level-indexed breakdowns, grouped into per-component ``Registry``
  objects (each collector server owns one; the in-process driver, the
  RPC leader, and the mesh leader own theirs) plus span-style timing
  contexts that mark "what is running right now" for the heartbeat.
- :mod:`.logs` — structured log emission: human-readable lines by
  default, JSON-lines via ``FHH_LOG_FORMAT=json``; stream and severity
  threshold are env/config knobs.
- :mod:`.heartbeat` — a periodic daemon thread that logs every live
  registry's active span (phase name, level, elapsed), so a wedged run
  shows exactly which phase and level it died in.
- :mod:`.report` — the end-of-run machine-readable report: per-level
  phase seconds, data-plane bytes sent/received, device-fetch counts,
  GC test counts, OT batch sizes, frontier/survivor sizes, checkpoint
  events — everything the registries accumulated, as one JSON document.
- :mod:`.hist` — fixed-bucket latency histograms (log-spaced, mergeable
  across registries/processes) feeding the ``slo`` sections of
  ``status`` and the run report: per-level crawl latency, per-verb RPC
  latency, ingest admit latency, window seal-to-hitters.
- :mod:`.trace` — cross-process distributed tracing: the leader mints a
  trace id per crawl/window, every verb carries a span id, and each
  process appends Chrome-trace events to a JSONL ring under
  ``FHH_TRACE_DIR``; ``python -m fuzzyheavyhitters_tpu.obs.trace merge``
  emits one clock-corrected Perfetto timeline.  ``FHH_PROFILE`` adds
  JAX profiler captures keyed to the same trace ids.

Env knobs (all optional):

- ``FHH_LOG_FORMAT``: ``human`` (default) | ``json`` (JSON-lines)
- ``FHH_LOG_STREAM``: ``stderr`` (default) | ``stdout`` | a file path
- ``FHH_LOG_LEVEL``: ``debug`` | ``info`` (default) | ``warn`` | ``error``
- ``FHH_HEARTBEAT_S``: heartbeat period in seconds (``0`` disables; the
  binaries default to 30 s when unset)
- ``FHH_RUN_REPORT``: path; when set, the binaries write the end-of-run
  report there
- ``FHH_TRACE_DIR``: directory; when set, every process appends trace
  events there (off = zero-cost, like ``FHH_DEBUG_GUARDS``);
  ``FHH_TRACE_RING`` bounds events per ring segment
- ``FHH_PROFILE``: directory; wrap each crawl (or only the levels in
  ``FHH_PROFILE_LEVELS=2,5``) in a ``jax.profiler`` capture
"""

from . import trace
from .heartbeat import start_heartbeat, stop_heartbeat
from .hist import Histogram
from .logs import configure as configure_logs, emit
from .metrics import Registry, all_registries, default_registry
from .report import (
    claim_report_path,
    exit_report,
    maybe_write_run_report,
    per_process_report_path,
    run_report,
    write_run_report,
)

__all__ = [
    "Histogram",
    "Registry",
    "all_registries",
    "claim_report_path",
    "configure_logs",
    "default_registry",
    "emit",
    "exit_report",
    "maybe_write_run_report",
    "per_process_report_path",
    "run_report",
    "start_heartbeat",
    "stop_heartbeat",
    "trace",
    "write_run_report",
]
