"""Structured telemetry for the crawl stack — dependency-free.

Four small pieces, one coherent layer (replacing the ad-hoc ``print``
taxonomy that left BENCH_r05's rc=124 postmortem with nothing but an XLA
platform warning):

- :mod:`.metrics` — named counters, gauges, and phase timers with
  level-indexed breakdowns, grouped into per-component ``Registry``
  objects (each collector server owns one; the in-process driver, the
  RPC leader, and the mesh leader own theirs) plus span-style timing
  contexts that mark "what is running right now" for the heartbeat.
- :mod:`.logs` — structured log emission: human-readable lines by
  default, JSON-lines via ``FHH_LOG_FORMAT=json``; stream and severity
  threshold are env/config knobs.
- :mod:`.heartbeat` — a periodic daemon thread that logs every live
  registry's active span (phase name, level, elapsed), so a wedged run
  shows exactly which phase and level it died in.
- :mod:`.report` — the end-of-run machine-readable report: per-level
  phase seconds, data-plane bytes sent/received, device-fetch counts,
  GC test counts, OT batch sizes, frontier/survivor sizes, checkpoint
  events — everything the registries accumulated, as one JSON document.
- :mod:`.hist` — fixed-bucket latency histograms (log-spaced, mergeable
  across registries/processes) feeding the ``slo`` sections of
  ``status`` and the run report: per-level crawl latency, per-verb RPC
  latency, ingest admit latency, window seal-to-hitters.
- :mod:`.trace` — cross-process distributed tracing: the leader mints a
  trace id per crawl/window, every verb carries a span id, and each
  process appends Chrome-trace events to a JSONL ring under
  ``FHH_TRACE_DIR``; ``python -m fuzzyheavyhitters_tpu.obs.trace merge``
  emits one clock-corrected Perfetto timeline.  ``FHH_PROFILE`` adds
  JAX profiler captures keyed to the same trace ids.
- :mod:`.exporter` — the LIVE plane: a zero-dependency Prometheus
  ``/metrics`` HTTP endpoint (``FHH_METRICS_PORT``; strictly zero-cost
  unset) serving every live registry's counters/gauges/timers plus the
  fixed-bucket histograms as ``_bucket`` series.
- :mod:`.devmem` — device-memory + XLA-compile telemetry: HBM
  in-use/watermark/delta gauges (live-arrays fallback on CPU),
  per-session key-plane residency bytes, and fresh-compile counters
  attributed to the active phase — a recompile past the warmup ladder
  is a named, counted event.
- :mod:`.alerts` — declarative threshold rules (tenant stall, SLO burn,
  ingest backlog, recompile-after-warmup, HBM high water) fired once
  per subject into the logs + trace ring, ``status.alerts``, and the
  run report's ``alerts`` section.
- :mod:`.ops` — ``python -m fuzzyheavyhitters_tpu.obs.ops top``: the
  one-screen live view scraping all three processes' /metrics and
  merging per-collection rows.

Env knobs (all optional):

- ``FHH_LOG_FORMAT``: ``human`` (default) | ``json`` (JSON-lines)
- ``FHH_LOG_STREAM``: ``stderr`` (default) | ``stdout`` | a file path
- ``FHH_LOG_LEVEL``: ``debug`` | ``info`` (default) | ``warn`` | ``error``
- ``FHH_HEARTBEAT_S``: heartbeat period in seconds (``0`` disables; the
  binaries default to 30 s when unset)
- ``FHH_RUN_REPORT``: path; when set, the binaries write the end-of-run
  report there
- ``FHH_TRACE_DIR``: directory; when set, every process appends trace
  events there (off = zero-cost, like ``FHH_DEBUG_GUARDS``);
  ``FHH_TRACE_RING`` bounds events per ring segment
- ``FHH_PROFILE``: directory; wrap each crawl (or only the levels in
  ``FHH_PROFILE_LEVELS=2,5``) in a ``jax.profiler`` capture
- ``FHH_METRICS_PORT``: base port; when set, each process serves
  ``/metrics`` on base + its tag offset (leader +0, s0 +1, s1 +2);
  ``0`` binds an ephemeral port (tests).  ``FHH_METRICS_HOST`` binds a
  non-loopback interface.
- ``FHH_ALERT_STALL_S`` / ``FHH_ALERT_LEVEL_P95_S`` /
  ``FHH_ALERT_BACKLOG_KEYS`` / ``FHH_ALERT_HBM_FRAC``: alert-rule
  thresholds (obs.alerts; defaults 120 / 2.0 / 100000 / 0.9)
"""

from . import alerts, devmem, exporter, trace
from .heartbeat import start_heartbeat, stop_heartbeat
from .hist import Histogram
from .logs import configure as configure_logs, emit
from .metrics import Registry, all_registries, default_registry
from .report import (
    claim_report_path,
    exit_report,
    maybe_write_run_report,
    per_process_report_path,
    run_report,
    write_run_report,
)

__all__ = [
    "Histogram",
    "Registry",
    "alerts",
    "all_registries",
    "claim_report_path",
    "devmem",
    "exporter",
    "configure_logs",
    "default_registry",
    "emit",
    "exit_report",
    "maybe_write_run_report",
    "per_process_report_path",
    "run_report",
    "start_heartbeat",
    "stop_heartbeat",
    "trace",
    "write_run_report",
]
