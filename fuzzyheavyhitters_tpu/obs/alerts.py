"""Declarative alert rules over the live telemetry plane.

The campaign's failure modes are known in advance — a wedged tenant, an
SLO burn, an ingest front door backing up, a recompile past the warmup
ladder, HBM near capacity.  This module turns each into a named,
threshold-gated rule evaluated over the same data the exporter and the
``status`` verb already read, so the FIRST occurrence is a structured
event in the logs and the trace ring (and the ``status.alerts`` /
run-report ``alerts`` rollups), not a post-mortem discovery.

Rules and their env-tunable thresholds (defaults in parentheses):

======================  ==========================  =====================
rule                    threshold env               fires when
======================  ==========================  =====================
``tenant_stall``        ``FHH_ALERT_STALL_S``       a session's
                        (120)                       ``last_progress_s``
                                                    exceeds the gap
``slo_burn``            ``FHH_ALERT_LEVEL_P95_S``   ``level_latency`` p95
                        (2.0)                       over budget
``ingest_backlog``      ``FHH_ALERT_BACKLOG_KEYS``  a session's unsealed
                        (100000)                    queue depth exceeds
                                                    the bound
``recompile_after_warmup``  (none: any)             ``fresh_compiles_post_
                                                    warmup`` > 0 (devmem)
``hbm_high_water``      ``FHH_ALERT_HBM_FRAC``      in-use/limit over the
                        (0.9)                       fraction (skipped when
                                                    the runtime reports no
                                                    capacity — XLA:CPU)
``migration_stuck``     ``FHH_ALERT_MIGRATION_``    a fleet migration's
                        ``STUCK_S`` (120)           inflight gauge older
                                                    than the budget
==========================================================================

Fire-once discipline: an alert is keyed ``(rule, subject)`` and emits
exactly once per process — the log line, the trace instant, and the
rollup entry mark the TRANSITION, so a stalled tenant produces one alert,
not one per scrape.  The full fired list stays available to ``status``
and the run report for the life of the process.

Evaluation is pull-based and cheap: the exporter runs the registry rules
on every scrape, the collector's ``status`` verb (and its /metrics
producer) runs the session rules over the same rows it already builds.
No thread, no timer — an idle process pays nothing.
"""

from __future__ import annotations

import os
import threading
import time

from . import logs
from . import trace as _trace
from ..utils import taint_guard
from .hist import Histogram
from .metrics import all_registries

# env knob -> default threshold; read per evaluation so tests (and a
# live operator) can retune without a process restart
ENV_STALL_S = ("FHH_ALERT_STALL_S", 120.0)
ENV_LEVEL_P95_S = ("FHH_ALERT_LEVEL_P95_S", 2.0)
ENV_BACKLOG_KEYS = ("FHH_ALERT_BACKLOG_KEYS", 100000.0)
ENV_HBM_FRAC = ("FHH_ALERT_HBM_FRAC", 0.9)
ENV_MIGRATION_STUCK_S = ("FHH_ALERT_MIGRATION_STUCK_S", 120.0)

_MAX_FIRED = 256  # rollup bound: alerts are transitions, not a log

_lock = threading.Lock()
_fired: list = []  # fhh-guard: _fired=_lock
_seen: set = set()  # fhh-guard: _seen=_lock
_dropped = 0  # fhh-guard: _dropped=_lock


def _threshold(knob: tuple[str, float]) -> float:
    env, default = knob
    raw = os.environ.get(env)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _fire(rule: str, subject: str, **ctx) -> None:
    global _dropped
    # alert subjects/context cross to logs + trace + the /metrics
    # exposition: assert no registered secret buffer rides along
    taint_guard.check((subject, ctx), sink="alert-fire")
    with _lock:
        if (rule, subject) in _seen:
            return
        _seen.add((rule, subject))
        rec = {"rule": rule, "subject": subject, "ts": round(time.time(), 3)}
        rec.update(ctx)
        _fired.append(rec)
        if len(_fired) > _MAX_FIRED:
            del _fired[0]
            _dropped += 1
    logs.emit(f"alert.{rule}", severity="warn", subject=subject, **ctx)
    if _trace.enabled():
        _trace.instant(f"alert:{rule}", "alerts", subject=subject, **ctx)


# -- rule evaluation -------------------------------------------------------


def evaluate_registries(regs=None) -> None:
    """The registry-walk rules: SLO burn, post-warmup recompiles, HBM
    high water.  Reads only thread-safe registry accessors."""
    p95_budget = _threshold(ENV_LEVEL_P95_S)
    hbm_frac = _threshold(ENV_HBM_FRAC)
    for reg in (regs if regs is not None else all_registries()):
        h = reg.hist("level_latency")
        if h is not None and h.count > 0:
            p95 = h.quantile(0.95)
            if p95 is not None and p95 > p95_budget:
                _fire(
                    "slo_burn", reg.name,
                    p95_s=round(p95, 4), budget_s=p95_budget,
                    samples=h.count,
                )
        post = reg.counter_value("fresh_compiles_post_warmup")
        if post:
            _fire("recompile_after_warmup", reg.name, compiles=int(post))
        in_use = reg.gauge_value("hbm_in_use_bytes")
        limit = reg.gauge_value("hbm_limit_bytes")
        if in_use and limit and in_use / limit > hbm_frac:
            _fire(
                "hbm_high_water", reg.name,
                in_use_bytes=int(in_use), limit_bytes=int(limit),
                frac=round(in_use / limit, 4), budget_frac=hbm_frac,
            )
        # stuck migration: the fleet placer sets this gauge to the
        # attempt's start instant and clears it to 0 on ANY outcome
        # (protocol/fleet.py) — a nonzero value older than the budget
        # means a transfer wedged mid-flight (source still authoritative,
        # destination half-imported: operator attention, not silence)
        since = reg.gauge_value("migration_inflight_since")
        if since:
            stuck_s = _threshold(ENV_MIGRATION_STUCK_S)
            age = time.time() - float(since)
            if age > stuck_s:
                _fire(
                    "migration_stuck", reg.name,
                    inflight_s=round(age, 3), budget_s=stuck_s,
                )


def evaluate_sessions(rows: dict, source: str) -> None:
    """The session-row rules over ``status.sessions.per_session`` rows
    (the collector builds them; ``source`` names the server so the
    fire-once key stays per-process-per-tenant)."""
    stall_s = _threshold(ENV_STALL_S)
    backlog = _threshold(ENV_BACKLOG_KEYS)
    for key, row in rows.items():
        subject = f"{source}/{key}"
        gap = row.get("last_progress_s")
        if gap is not None and gap > stall_s:
            _fire(
                "tenant_stall", subject,
                last_progress_s=gap, budget_s=stall_s,
                phase=row.get("phase"), level=row.get("level"),
            )
        depth = row.get("queue_depth")
        if depth is not None and depth > backlog:
            _fire(
                "ingest_backlog", subject,
                queue_depth=int(depth), budget_keys=int(backlog),
            )


# -- read sides ------------------------------------------------------------


def fired() -> list:
    """Every alert fired so far in this process (bounded; oldest beyond
    the cap are dropped and counted)."""
    with _lock:
        return list(_fired)


def status_section() -> dict:
    """The ``status.alerts`` rollup: bounded, newest last."""
    with _lock:
        return {
            "count": len(_seen),
            "dropped": _dropped,
            "fired": list(_fired),
        }


def report_section() -> dict | None:
    """The run-report ``alerts`` section — None when nothing ever fired
    (pre-alert reports keep their exact old shape)."""
    with _lock:
        if not _seen:
            return None
        return {
            "count": len(_seen),
            "dropped": _dropped,
            "fired": list(_fired),
        }


def metrics_lines() -> list[str]:
    """Alert state as exposition lines for the /metrics exporter (which
    also calls :func:`evaluate_registries` per scrape)."""
    from . import exporter  # late: exporter imports hist/metrics only

    with _lock:
        recs = list(_fired)
    by_rule: dict[str, int] = {}
    for rec in recs:
        by_rule[rec["rule"]] = by_rule.get(rec["rule"], 0) + 1
    lines = ["# TYPE fhh_alerts_fired_total counter"]
    for rule in sorted(by_rule):
        lines.append(
            f'fhh_alerts_fired_total{{rule="{exporter._esc(rule)}"}}'
            f" {by_rule[rule]}"
        )
    lines.append("# TYPE fhh_alert_active gauge")
    for rec in recs:
        lines.append(
            f'fhh_alert_active{{rule="{exporter._esc(rec["rule"])}",'
            f'subject="{exporter._esc(rec["subject"])}"}} 1'
        )
    return lines


def _reset_for_tests() -> None:
    global _dropped
    with _lock:
        _fired.clear()
        _seen.clear()
        _dropped = 0
