"""Heartbeat: a daemon thread that names the phase a wedged run died in.

Every ``interval`` seconds it walks the live registries and emits one
``heartbeat`` event per ACTIVE span (registry name, span name, level,
elapsed seconds), or a single idle heartbeat when nothing is running —
so an rc=124 postmortem reads the log tail and sees, e.g.::

    [fhh 04:12:07 info] heartbeat registry=server0 span=gc_ot level=311 elapsed_s=412.0312

instead of an XLA platform warning and silence (the BENCH_r05 failure
mode this module exists for).

``start_heartbeat`` is a module-level singleton: binaries call it
unconditionally with their default period and ``FHH_HEARTBEAT_S``
overrides (``0`` disables).  The thread is a daemon AND stops cleanly
via :func:`stop_heartbeat` (tests assert both: it fires, and it stops).
"""

from __future__ import annotations

import os
import threading

from . import logs, metrics
from . import trace as tracemod


class Heartbeat(threading.Thread):
    def __init__(self, interval: float):
        super().__init__(name="fhh-heartbeat", daemon=True)
        self.interval = interval
        self._stop_evt = threading.Event()

    def run(self) -> None:
        while not self._stop_evt.wait(self.interval):
            self.beat()

    def beat(self) -> None:
        """One heartbeat sweep (factored out so tests can fire it
        synchronously)."""
        active = False
        for reg in metrics.all_registries():
            sp = reg.current_span()
            if sp is None:
                continue
            active = True
            logs.emit(
                "heartbeat",
                registry=reg.name,
                span=sp.name,
                level=sp.level,
                elapsed_s=sp.elapsed(),
            )
            # wedge markers in the merged trace: a heartbeat instant per
            # active span puts "what was running" on the Perfetto
            # timeline even when the process never exits cleanly
            if tracemod.enabled():
                tracemod.instant(
                    "heartbeat", comp=reg.name,
                    span=sp.name, level=sp.level,
                    elapsed_s=round(sp.elapsed(), 3),
                )
        if not active:
            logs.emit("heartbeat", idle=True)

    def stop(self) -> None:
        self._stop_evt.set()


_hb_lock = threading.Lock()
_hb: Heartbeat | None = None  # fhh-guard: _hb=_hb_lock


def start_heartbeat(default_s: float = 30.0) -> Heartbeat | None:
    """Start (or return) the process heartbeat.  ``FHH_HEARTBEAT_S``
    overrides ``default_s``; a period <= 0 disables and returns None."""
    global _hb
    try:
        interval = float(os.environ.get("FHH_HEARTBEAT_S", default_s))
    except ValueError:
        interval = default_s
    if interval <= 0:
        return None
    with _hb_lock:
        if _hb is None or not _hb.is_alive():
            _hb = Heartbeat(interval)
            _hb.start()
        return _hb


def stop_heartbeat() -> None:
    global _hb
    with _hb_lock:
        if _hb is not None:
            _hb.stop()
            _hb = None
