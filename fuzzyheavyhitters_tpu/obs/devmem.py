"""Device-memory and XLA-compile telemetry: the flagship-run risk gauges.

The 1M-client flagship plan (ROADMAP) carries two known physical risks
that were only visible post-mortem before this module: HBM residency
("1.51 chips of key storage") and recompile storms past the warmup
ladder.  Both become live, named numbers here:

- :func:`sample` reads per-device memory stats (``device.memory_stats()``
  — TPU/GPU runtimes report ``bytes_in_use``/``bytes_limit``) and sets
  ``hbm_in_use_bytes`` / ``hbm_watermark_bytes`` / ``hbm_delta_bytes``
  (and ``hbm_limit_bytes`` when the runtime knows its capacity) on a
  registry.  XLA:CPU reports no memory stats, so the fallback sums
  ``jax.live_arrays()`` — process-wide tracked-array bytes, the honest
  CPU analogue.  A ``phase`` argument adds a per-phase watermark
  (``hbm_watermark_bytes:<phase>`` — the colon becomes a ``key`` label
  at export).
- :func:`tree_nbytes` sizes a pytree of arrays; the session layer uses
  it to publish ``key_plane_bytes`` per collection when the key plane
  concatenates (sessions.concat_keys).
- :func:`install_compile_listener` hooks JAX's monitoring event
  ``/jax/core/compile/backend_compile_duration`` (fires once per FRESH
  backend compile — persistent-cache hits do not fire it).  The event
  carries no program name, so each compile is attributed to the
  innermost active obs span (the phase taxonomy IS our program naming:
  ``level``/``warmup``/``setup``/...), counted as ``fresh_compiles`` +
  ``fresh_compiles:<span>`` on the default registry.  After
  :func:`note_warmup_done` (the warmup verb's last act), compiles also
  count into ``fresh_compiles_post_warmup`` — the named, counted event
  the ``recompile_after_warmup`` alert rule watches.

``jax`` is imported lazily inside each function: the obs package stays
importable (and the exporter/alert plane usable) in jax-free tooling
contexts.
"""

from __future__ import annotations

import threading

from . import logs
from .metrics import Registry, all_registries, default_registry

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
# fhh-guard: _state=_lock
_state = {"listener": False, "warmup_done": False}


def device_bytes() -> tuple[int, int | None]:
    """(bytes in use, capacity or None) summed over local devices.
    Runtimes without memory stats (XLA:CPU) fall back to live-array
    bytes with an unknown capacity."""
    import jax

    in_use, limit, got = 0, 0, False
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        # fhh-lint: disable=broad-except (telemetry probe: a backend
        # without the stats API must degrade to the fallback, not crash)
        except Exception:
            ms = None
        if ms and "bytes_in_use" in ms:
            got = True
            in_use += int(ms["bytes_in_use"])
            limit += int(ms.get("bytes_limit", 0))
    if got:
        return in_use, (limit or None)
    return live_array_bytes(), None


def live_array_bytes() -> int:
    """Process-wide bytes of live tracked jax arrays (the CPU fallback)."""
    import jax

    return int(sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays()))


def tree_nbytes(tree) -> int:
    """Total bytes of the array leaves of a pytree (0 for None)."""
    if tree is None:
        return 0
    import jax

    return int(
        sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree.leaves(tree)
        )
    )


def sample(reg: Registry | None = None, phase: str | None = None) -> int:
    """One memory sample onto ``reg`` (default registry when None):
    sets the in-use gauge, advances the watermark, records the delta
    since the previous sample, and (with ``phase``) a per-phase
    watermark.  Returns bytes in use."""
    reg = reg if reg is not None else default_registry()
    in_use, limit = device_bytes()
    prev = reg.gauge_value("hbm_in_use_bytes") or 0
    reg.gauge("hbm_in_use_bytes", in_use)
    reg.gauge("hbm_delta_bytes", in_use - prev)
    wm = reg.gauge_value("hbm_watermark_bytes") or 0
    if in_use > wm:
        reg.gauge("hbm_watermark_bytes", in_use)
    if limit:
        reg.gauge("hbm_limit_bytes", limit)
    if phase:
        key = f"hbm_watermark_bytes:{phase}"
        if in_use > (reg.gauge_value(key) or 0):
            reg.gauge(key, in_use)
    return in_use


# -- fresh-compile accounting ---------------------------------------------


def _span_name() -> str:
    """The innermost active span name across every live registry — the
    phase a compile is attributed to (``unknown`` outside any span)."""
    for reg in all_registries():
        sp = reg.current_span()
        if sp is not None:
            return sp.name
    return "unknown"


def _on_event(event: str, duration: float, **_kw) -> None:
    if event != _COMPILE_EVENT:
        return
    reg = default_registry()
    name = _span_name()
    reg.count("fresh_compiles")
    reg.count(f"fresh_compiles:{name}")
    reg.timer_add("xla_compile", duration)
    with _lock:
        warm = _state["warmup_done"]
    if warm:
        reg.count("fresh_compiles_post_warmup")
        logs.emit(
            "compile.post_warmup", severity="debug",
            program=name, seconds=round(duration, 4),
        )


def install_compile_listener() -> None:
    """Register the per-compile listener once per process.  JAX offers
    no unregister, so this is a one-way, idempotent switch — same
    contract as utils.compile_cache.backend_compiles()."""
    with _lock:
        if _state["listener"]:
            return
        _state["listener"] = True
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_event)


def note_warmup_done() -> None:
    """Mark the warmup ladder complete: every fresh compile after this
    is a ``fresh_compiles_post_warmup`` event (and alert fodder)."""
    with _lock:
        _state["warmup_done"] = True


def warmup_done() -> bool:
    with _lock:
        return _state["warmup_done"]


def _reset_for_tests() -> None:
    """Clear the warmup flag (the listener itself cannot unregister)."""
    with _lock:
        _state["warmup_done"] = False
