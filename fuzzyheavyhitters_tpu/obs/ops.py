"""fhh-ops CLI: the one-screen live view over the /metrics plane.

``python -m fuzzyheavyhitters_tpu.obs.ops top`` scrapes the leader and
both collector servers (``FHH_METRICS_PORT`` base, +1, +2 — or explicit
``--targets``), merges per-collection rows across processes, and renders
one screen: alerts first, then a session table (last progress, queue
depth, level-latency p95 reconstructed from the shared fixed buckets),
then per-registry headline counters.  ``--once`` prints a single frame
(tests, cron); the default loops with a clear between frames.

The exposition parser and the bucket->Histogram reconstruction live here
as importable pure functions — the round-trip tests use them to prove a
scrape carries exactly the quantiles the run report computes.
"""

from __future__ import annotations

import argparse
import math
import os
import re
import sys
import time
import urllib.request

from .hist import BUCKET_BOUNDS, Histogram

_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> list[tuple[str, dict, float]]:
    """Exposition text -> ``[(name, labels, value), ...]`` (comments and
    malformed lines skipped — a scrape parser must be forgiving)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if m is None:
            continue
        labels = {
            k: v.replace('\\"', '"').replace("\\n", "\n").replace("\\\\", "\\")
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.append((m.group("name"), labels, value))
    return out


def buckets_to_hist(samples: list[tuple[dict, float]]) -> Histogram:
    """Rebuild an :class:`obs.hist.Histogram` from one series' scraped
    cumulative ``_bucket`` samples (``le`` label keyed).  The shared
    BUCKET_BOUNDS make this exact for counts; sum rides via
    :func:`hist_from_series` when the ``_sum``/``_count`` samples are
    supplied too."""
    by_le: dict[float, int] = {}
    inf_count = 0
    for labels, value in samples:
        le = labels.get("le")
        if le is None:
            continue
        if le == "+Inf":
            inf_count = int(value)
        else:
            by_le[float(le)] = int(value)
    h = Histogram()
    prev = 0
    for i, bound in enumerate(BUCKET_BOUNDS):
        cum = by_le.get(float(format(bound, ".10g")), prev)
        h.counts[i] = cum - prev
        prev = cum
    h.counts[len(BUCKET_BOUNDS)] = max(0, inf_count - prev)
    h.count = inf_count
    return h


def hist_from_series(
    buckets: list[tuple[dict, float]],
    sum_s: float | None = None,
    count: int | None = None,
) -> Histogram:
    """Buckets + optional ``_sum``/``_count`` -> a mergeable Histogram.
    min/max are not on the wire; they re-derive conservatively from the
    occupied bucket range so quantile clamping stays sane."""
    h = buckets_to_hist(buckets)
    if count is not None:
        h.count = int(count)
    if sum_s is not None:
        h.sum = float(sum_s)
    lo = hi = None
    for i, c in enumerate(h.counts):
        if c:
            if lo is None:
                lo = i
            hi = i
    if lo is not None:
        h.min = 0.0 if lo == 0 else BUCKET_BOUNDS[lo - 1]
        h.max = h.sum if hi >= len(BUCKET_BOUNDS) else BUCKET_BOUNDS[hi]
    else:
        h.min, h.max = math.inf, 0.0
    return h


# -- scraping --------------------------------------------------------------


def default_targets() -> list[str]:
    base = int(os.environ.get("FHH_METRICS_PORT", "0") or 0)
    if not base:
        return []
    host = os.environ.get("FHH_METRICS_HOST", "127.0.0.1")
    return [f"{host}:{base + off}" for off in (0, 1, 2)]


def scrape(target: str, timeout_s: float = 2.0) -> list[tuple[str, dict, float]]:
    """One target's parsed samples; [] when unreachable (a dead process
    is a row gap in ``top``, not a crash)."""
    try:
        with urllib.request.urlopen(
            f"http://{target}/metrics", timeout=timeout_s
        ) as resp:
            return parse_prometheus(resp.read().decode("utf-8", "replace"))
    except OSError:
        return []


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return f"{v:.1f}TiB"


def render_top(samples_by_target: dict) -> str:
    """Merge every target's samples into the one-screen frame."""
    lines = [
        "fhh-ops top  "
        + time.strftime("%H:%M:%S")
        + "  targets: "
        + " ".join(
            f"{t}({'up' if s else 'down'})"
            for t, s in samples_by_target.items()
        )
    ]
    allsamp = [
        (t, n, lb, v)
        for t, samp in samples_by_target.items()
        for (n, lb, v) in samp
    ]
    alerts = [
        (lb.get("rule", "?"), lb.get("subject", "?"))
        for (_t, n, lb, _v) in allsamp
        if n == "fhh_alert_active"
    ]
    if alerts:
        lines.append("ALERTS:")
        for rule, subject in sorted(set(alerts)):
            lines.append(f"  !! {rule:<24} {subject}")
    else:
        lines.append("alerts: none")
    # per-(registry, collection) session rows, merged across targets
    rows: dict[tuple, dict] = {}

    def row(lb):
        key = (lb.get("registry", "?"), lb.get("collection", "default"))
        return rows.setdefault(key, {})

    hist_parts: dict[tuple, dict] = {}
    for _t, name, lb, v in allsamp:
        if name == "fhh_session_last_progress_seconds":
            row(lb)["last_progress_s"] = v
        elif name == "fhh_session_queue_depth_keys":
            row(lb)["queue_depth"] = v
        elif name == "fhh_key_plane_bytes":
            row(lb)["key_plane"] = v
        elif name == "fhh_level_latency_seconds_bucket":
            key = (lb.get("registry", "?"), lb.get("collection", "default"))
            hist_parts.setdefault(key, {"b": [], "s": None, "c": None})[
                "b"
            ].append((lb, v))
        elif name == "fhh_level_latency_seconds_sum":
            key = (lb.get("registry", "?"), lb.get("collection", "default"))
            hist_parts.setdefault(key, {"b": [], "s": None, "c": None})[
                "s"
            ] = v
        elif name == "fhh_level_latency_seconds_count":
            key = (lb.get("registry", "?"), lb.get("collection", "default"))
            hist_parts.setdefault(key, {"b": [], "s": None, "c": None})[
                "c"
            ] = v
        elif name == "fhh_hbm_in_use_bytes":
            row(lb)["hbm"] = v
    for key, parts in hist_parts.items():
        h = hist_from_series(parts["b"], parts["s"], parts["c"])
        if h.count:
            rows.setdefault(key, {})["p95_s"] = h.quantile(0.95)
            rows.setdefault(key, {})["levels"] = h.count
    if rows:
        lines.append(
            f"{'registry':<12} {'collection':<16} {'progress':>9} "
            f"{'queue':>8} {'levels':>7} {'lvl p95':>9} {'hbm':>10}"
        )
        for (reg, coll), r in sorted(rows.items()):
            gap = r.get("last_progress_s")
            p95 = r.get("p95_s")
            lines.append(
                f"{reg:<12} {coll:<16} "
                f"{(f'{gap:.1f}s' if gap is not None else '-'):>9} "
                f"{int(r.get('queue_depth', 0)):>8} "
                f"{int(r.get('levels', 0)):>7} "
                f"{(f'{p95:.3f}s' if p95 is not None else '-'):>9} "
                f"{(_fmt_bytes(r['hbm']) if 'hbm' in r else '-'):>10}"
            )
    # headline counters per registry (totals only, merged by max per
    # target — each process reports its own registries exactly once)
    heads: dict[tuple, float] = {}
    for _t, name, lb, v in allsamp:
        if name in (
            "fhh_fresh_compiles_total",
            "fhh_fresh_compiles_post_warmup_total",
            "fhh_data_bytes_sent_total",
            "fhh_ingest_admitted_total",
        ):
            heads[(lb.get("registry", "?"), name)] = max(
                heads.get((lb.get("registry", "?"), name), 0.0), v
            )
    for (reg, name), v in sorted(heads.items()):
        lines.append(f"  {reg:<12} {name} {int(v)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fuzzyheavyhitters_tpu.obs.ops")
    sub = ap.add_subparsers(dest="cmd", required=True)
    top = sub.add_parser("top", help="live one-screen view over /metrics")
    top.add_argument(
        "--targets",
        help="comma list of host:port (default: FHH_METRICS_PORT base,+1,+2)",
    )
    top.add_argument("--once", action="store_true", help="print one frame")
    top.add_argument("--interval", type=float, default=2.0)
    args = ap.parse_args(argv)
    targets = (
        args.targets.split(",") if args.targets else default_targets()
    )
    if not targets:
        print(
            "no targets: set FHH_METRICS_PORT or pass --targets",
            file=sys.stderr,
        )
        return 2
    while True:
        frame = render_top({t: scrape(t) for t in targets})
        if args.once:
            print(frame)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
